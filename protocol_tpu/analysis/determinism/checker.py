"""Pass 13 — the determinism wall.

The pod substrate (PR 16) and the durability plane (PR 14) stake
correctness on bit-identical state: per-epoch residuals and score
digests must match across hosts before host 0 seals a pod manifest,
WAL replay must reconverge to a control-identical fixed point, and
pooled proofs must be byte-identical to in-process ones.  Nothing
before this pass stopped the next PR from introducing a set-iteration,
an unsorted ``os.listdir``, a ``hash()``-keyed ordering, or a
nondeterministic HLO reduction that silently diverges hosts until a
manifest seal fails in production.

Two static legs:

- **AST** (``ast_walk.py``): divergence-feasible Python sources over
  the trees that feed bit-identity sinks — see the module docstring
  there for the five rules.
- **HLO**: rides the pass-8/12 memoized lowerings
  (``comm.lowering.build_cases`` — the executables are compiled once
  and shared with passes 8 and 12) and asserts every compiled converge
  entry is replay-stable:

  - ``hlo-nondeterministic-scatter`` — a scatter instruction without
    ``unique_indices=true``: duplicate-index scatter combines in
    whatever order the backend schedules, so two hosts (or two runs)
    can legally produce different f32 sums from the same operands;
  - ``hlo-reduce-precision`` — a ``reduce-precision`` op inside a
    converge module: the f32 fixed-point path must carry full
    precision end to end, or residual thresholds stop being
    host-identical;
  - ``hlo-nondeterministic-compile`` — each backend is compiled
    **twice** (the memoized pass-8 executable plus one fresh compile
    at the first scale) and the two modules are diffed after
    canonicalization (SSA value names are renumbered in order of first
    appearance, so per-process naming counters cancel out).  Any
    surviving drift means compilation itself is an entropy source —
    the one failure mode no amount of Python-side seeding can fix.

Waiver doctrine and section shape mirror pass 12; the runtime half
(``tools/divergence_probe.py``) closes the loop by replaying the full
2-process pod under perturbed schedules and asserting every sink
digest identical.
"""

from __future__ import annotations

import difflib
import re
from typing import Any

from ..report import Finding
from ..comm.lowering import COMM_BUILDERS, COMM_SCALES, build_cases
from .ast_walk import DET_AST_RULES, run_det_ast_pass
from .waivers import DET_WAIVERS


def _finding(rule: str, message: str, backend: str | None = None,
             file: str | None = None, line: int | None = None,
             severity: str = "error") -> Finding:
    return Finding(
        pass_name="determinism", rule=rule, severity=severity,
        message=message, backend=backend, file=file, line=line,
    )


# -- HLO canonicalization ---------------------------------------------------

#: SSA value names in HLO text: ``%fusion.123``, ``%param.0``,
#: ``%add.7`` — the numeric suffixes come from a per-process naming
#: counter, so two compiles of the same program legally differ in them.
_HLO_ID = re.compile(r"%[A-Za-z_][A-Za-z0-9_.\-]*")
#: Unnamed computation ids (``ENTRY %main.42``) share the same pattern;
#: buffer-donation comments carry absolute addresses we also drop.
_HLO_COMMENT = re.compile(r"\s*(//|/\*).*$")


def canonicalize_hlo(text: str) -> str:
    """Rename every SSA value name to ``%vN`` in order of first
    appearance and strip trailing comments, so two compiles of the same
    program map to the same text and any surviving difference is a real
    structural drift."""
    mapping: dict[str, str] = {}

    def rename(match: re.Match[str]) -> str:
        name = match.group(0)
        if name not in mapping:
            mapping[name] = f"%v{len(mapping)}"
        return mapping[name]

    lines = []
    for line in text.splitlines():
        line = _HLO_COMMENT.sub("", line)
        lines.append(_HLO_ID.sub(rename, line))
    return "\n".join(lines)


def diff_canonical(text_a: str, text_b: str, *, context: int = 1) -> str | None:
    """Canonicalize both module texts and return ``None`` when they
    match, else a short unified-diff excerpt naming the first drift."""
    a, b = canonicalize_hlo(text_a), canonicalize_hlo(text_b)
    if a == b:
        return None
    diff = difflib.unified_diff(
        a.splitlines(), b.splitlines(),
        fromfile="compile-1", tofile="compile-2",
        lineterm="", n=context,
    )
    excerpt = [line for line in diff][:12]
    return "\n".join(excerpt)


# -- HLO instruction rules --------------------------------------------------

_SCATTER_OP = re.compile(r"=\s*\S+\s+scatter\(")
_REDUCE_PRECISION_OP = re.compile(r"=\s*\S+\s+reduce-precision\(")


def scan_module_text(backend: str, module_text: str) -> tuple[list[Finding], dict]:
    """Instruction-level determinism scan of one compiled module.
    Returns ``(findings, stats record)``."""
    findings: list[Finding] = []
    scatter_ops = 0
    reduce_precision_ops = 0
    for i, line in enumerate(module_text.splitlines(), start=1):
        if _SCATTER_OP.search(line):
            scatter_ops += 1
            if "unique_indices=true" not in line:
                findings.append(_finding(
                    "hlo-nondeterministic-scatter",
                    f"scatter at module line {i} lacks "
                    "unique_indices=true — duplicate-index updates "
                    "combine in backend schedule order, so two hosts can "
                    "legally produce different f32 sums from identical "
                    "operands; segment the indices (or assert uniqueness "
                    "at plan build) before this reaches the epoch loop",
                    backend, line=i,
                ))
        if _REDUCE_PRECISION_OP.search(line):
            reduce_precision_ops += 1
            findings.append(_finding(
                "hlo-reduce-precision",
                f"reduce-precision at module line {i} inside a converge "
                "module — the f32 fixed-point path must carry full "
                "precision end to end or residual thresholds stop being "
                "host-identical",
                backend, line=i,
            ))
    return findings, {
        "scatter_ops": scatter_ops,
        "reduce_precision_ops": reduce_precision_ops,
    }


def check_recompile(backend: str, text_a: str, text_b: str) -> list[Finding]:
    """The double-compile cross-check: canonical-diff two compiles of
    the same backend entry; drift is ``hlo-nondeterministic-compile``."""
    excerpt = diff_canonical(text_a, text_b)
    if excerpt is None:
        return []
    return [_finding(
        "hlo-nondeterministic-compile",
        f"two compiles of the {backend!r} converge entry disagree after "
        "canonicalization — compilation itself is an entropy source, "
        "the one failure mode no Python-side seeding can fix; first "
        f"drift:\n{excerpt}",
        backend,
    )]


# -- waivers ----------------------------------------------------------------


def _apply_waivers(findings: list[Finding]) -> tuple[list[Finding], list[dict], list[dict]]:
    """Split findings into (live, waived records, stale records) using
    the enumerated DET_WAIVERS table — pass-7 doctrine."""
    live: list[Finding] = []
    waived: list[dict] = []
    matched: set[int] = set()
    for f in findings:
        hit = next(
            (
                (i, w)
                for i, w in enumerate(DET_WAIVERS)
                if w.matches(f.rule, f.file or "", f.message)
            ),
            None,
        )
        if hit is None:
            live.append(f)
        else:
            matched.add(hit[0])
            waived.append({
                "rule": f.rule, "file": f.file, "line": f.line,
                "symbol": hit[1].symbol, "reason": hit[1].reason,
            })
    stale = [
        {"symbol": w.symbol, "rule": w.rule, "reason": w.reason}
        for i, w in enumerate(DET_WAIVERS)
        if i not in matched
    ]
    return live, waived, stale


# -- the pass ---------------------------------------------------------------


def run_determinism_pass(
    backends: list[str] | None = None,
    *,
    include_zk: bool = False,
) -> tuple[list[Finding], dict[str, Any]]:
    """Run both static legs and return ``(findings, determinism
    section)`` for ANALYSIS.json.  ``backends`` narrows the HLO leg (and
    skips the AST leg) — the pass-12 subset-run convention.
    ``include_zk`` keeps the zk.graft proving kernels in the default
    HLO leg; without it they are filtered out of COMM_BUILDERS (pass 1
    registers their recipes in-process, but their EC compiles do not
    fit the default self-budget)."""
    findings: list[Finding] = []
    section: dict[str, Any] = {"backends": {}}

    from ..zk_lowering import register as _register_zk, zk_kernel_names

    zk_names = set(zk_kernel_names())
    if include_zk or (backends and set(backends) & zk_names):
        _register_zk()
    if backends is None:
        targets = [
            name for name in COMM_BUILDERS
            if include_zk or name not in zk_names
        ]
    else:
        targets = backends
    for name in targets:
        if name not in COMM_BUILDERS:
            section["backends"][name] = {"status": "no-recipe"}
            continue
        try:
            cases = build_cases(name)
        except Exception as exc:  # noqa: BLE001 - report, don't crash the gate
            section["backends"][name] = {
                "status": "lowering-failed", "error": repr(exc),
            }
            findings.append(_finding(
                "det-lowering-failure",
                f"compiling the step failed: {exc!r}", name,
            ))
            continue
        record: dict[str, Any] = {"status": "checked", "scales": []}
        for case in cases:
            case_findings, stats = scan_module_text(name, case.module_text)
            findings.extend(case_findings)
            record["scales"].append({
                "dims": case.dims,
                **stats,
                "violations": len(case_findings),
            })
        # Double-compile cross-check at the first scale only: the
        # memoized pass-8 executable vs one fresh compile — bypassing
        # the memo on purpose.  First scale bounds the added analyzer
        # cost (the windowed Pallas-interpret compiles dominate the
        # 120 s self-budget) while still exercising the full real
        # lowering path a second time.
        recipe, _two_scale = COMM_BUILDERS[name]
        try:
            fresh = recipe(*COMM_SCALES[0])
        except Exception as exc:  # noqa: BLE001
            section["backends"][name] = {
                "status": "recompile-failed", "error": repr(exc),
            }
            findings.append(_finding(
                "det-lowering-failure",
                f"fresh recompile for the drift check failed: {exc!r}",
                name,
            ))
            continue
        drift = check_recompile(name, cases[0].module_text, fresh.module_text)
        findings.extend(drift)
        record["recompile_drift"] = bool(drift)
        section["backends"][name] = record

    if backends is None:
        ast_findings, n_files = run_det_ast_pass()
        findings.extend(ast_findings)
        section["files_scanned"] = n_files

    live, waived, stale = _apply_waivers(findings)
    if backends is not None:
        # A backend-subset run never evaluates the AST leg, so the
        # staleness of an AST-rule waiver cannot be judged there —
        # only waivers whose domain this run covered may go stale.
        stale = [s for s in stale if s["rule"] not in DET_AST_RULES]
    for entry in stale:
        # A dead waiver is itself a gate failure — pass-7 doctrine,
        # enforced in every run that evaluates its table.
        live.append(_finding(
            "stale-waiver",
            f"determinism waiver {entry['symbol']!r} ({entry['rule']}) "
            "matches no live finding; a fixed divergence source must "
            "take its waiver with it",
            None,
        ))
    section["waived"] = waived
    section["stale_waivers"] = stale
    return live, section


__all__ = [
    "canonicalize_hlo",
    "check_recompile",
    "diff_canonical",
    "run_determinism_pass",
    "scan_module_text",
]
