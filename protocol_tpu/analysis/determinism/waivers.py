"""Explicit pass-13 waivers — same doctrine as the pass-7/8/12 tables:
every suppression is enumerated with its rationale, emitted into
ANALYSIS.json's ``determinism.waived`` list, and **stale-tested** in
every run that evaluates the table — a waiver that no longer matches a
live finding is itself an error (``stale-waiver``), so a fixed
divergence source takes its waiver with it.
"""

from __future__ import annotations

from ..concurrency.waivers import Waiver

#: (rule, file substring, message substring) -> rationale — see
#: :class:`~protocol_tpu.analysis.concurrency.waivers.Waiver`.
DET_WAIVERS: tuple[Waiver, ...] = (
    Waiver(
        rule="unseeded-rng",
        file="protocol_tpu/node/ethereum.py",
        symbol="random.Random",
        reason=(
            "ChainEventSource's retry-backoff jitter RNG is unseeded on "
            "purpose: jitter exists to DE-correlate hosts (every host "
            "retrying an RPC on the same schedule is the thundering "
            "herd the backoff is there to break), so seeding it from "
            "the shared protocol seed would be the bug.  The draw "
            "feeds only sleep durations inside the retry loop — it "
            "never reaches a WAL record, checkpoint column, manifest, "
            "job seed, or partition key, which is the bit-identity "
            "plane this pass protects.  The divergence probe "
            "(tools/divergence_probe.py) replays the full pod twice "
            "with this RNG live and proves every sink digest "
            "bit-identical regardless."
        ),
    ),
)

__all__ = ["DET_WAIVERS"]
