"""Pass 1 — the jaxpr invariant analyzer.

For every backend in the ``trust/backend.py`` registry (composites
expanded, e.g. ``tpu-sharded:tpu-windowed`` under the virtual CPU
mesh), trace its per-iteration step function to a closed jaxpr on a
small synthetic graph, walk it with ``jaxpr_walk``, and check the
declarative :data:`~protocol_tpu.analysis.budget.KERNEL_INVARIANTS`
budget the kernel module declared for it:

- random-gather budget (gathers without ``indices_are_sorted``);
- size-classed gather budgets, including the single-pass boundary
  bridge's "exactly one streaming ``(S, 2)`` sorted+unique read, one
  ``S``-sized random permutation" contract (PERF.md §8);
- scatter budget (the windowed/CSR steps are scatter-free by design);
- no float64 avals (TPU f64 is emulated — a silent 10× rot);
- no host callbacks inside the jit'd loop;
- ``psum`` count and placement (exactly one, only under ``shard_map``,
  for the sharded composites; zero elsewhere);
- donated-argument aliasing actually materialized in the lowered
  computation (``tf.aliasing_output`` / ``jax.buffer_donor``).

A registered jax backend with no declared budget is itself an error —
the gate every future backend inherits.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable

from .budget import KERNEL_INVARIANTS, NON_JAX_BACKENDS, KernelBudget
from .jaxpr_walk import (
    CALLBACK_PRIMITIVES,
    PSUM_PRIMITIVES,
    SCATTER_PRIMITIVES,
    EqnSite,
    collect_primitives,
    has_f64,
    iter_eqns,
    source_site,
)
from .report import Finding

#: Donation markers jax stamps on lowered (StableHLO) inputs.
_DONATION_MARKS = ("tf.aliasing_output", "jax.buffer_donor")


@dataclass
class TraceCase:
    """One backend's traced step plus the context to interpret it."""

    backend: str
    jaxpr: Any  # closed jaxpr of the per-iteration step (or full run)
    #: Named sizes resolving :class:`GatherBudget` dims, e.g.
    #: ``{"edges": 8993, "n_segments": 1575}``.
    dims: dict[str, int] = field(default_factory=dict)
    #: Lowered text of the jit'd converge entry point (donation check);
    #: None when the budget declares no donated args.
    lowered_text: str | None = None


def _synthetic_graph():
    """Small scale-free graph every trace shares: multi-window N, forced
    dangling peers, sizes chosen so the budget dimensions stay
    distinguishable (asserted in the windowed recipes)."""
    import numpy as np

    from ..models.graphs import scale_free
    from ..trust.graph import TrustGraph

    g = scale_free(1500, 9000, seed=2)
    keep = ~np.isin(g.src, np.asarray([0, 17, 1499], dtype=np.int32))
    return TrustGraph(g.n, g.src[keep], g.dst[keep], g.weight[keep], g.pre_trusted)


def _normalized(graph):
    import numpy as np

    from ..trust.graph import TrustGraph

    g = graph.drop_self_edges()
    w, dangling = g.row_normalized()
    gs = TrustGraph(g.n, g.src, g.dst, w, g.pre_trusted).sorted_by_dst()
    return g, gs, w, dangling.astype(np.float32)


def _trace_dense(graph) -> TraceCase:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..ops.dense import converge_dense

    rng = np.random.default_rng(0)
    n = 64
    m = rng.random((n, n)).astype(np.float32)
    m /= m.sum(axis=0, keepdims=True)
    t = np.full(n, 1.0 / n, np.float32)
    jaxpr = jax.make_jaxpr(lambda mm, tt: converge_dense(mm, tt, 4))(
        jnp.asarray(m), jnp.asarray(t)
    )
    return TraceCase("tpu-dense", jaxpr, dims={"n": n})


def _trace_sparse(graph) -> TraceCase:
    import jax
    import jax.numpy as jnp

    from ..ops.sparse import converge_sparse, power_step_coo

    g, gs, w, dangling = _normalized(graph)
    p = g.pre_trust_vector()
    args = (
        jnp.asarray(gs.src),
        jnp.asarray(gs.dst),
        jnp.asarray(gs.weight),
        jnp.asarray(p),
        jnp.asarray(p),
        jnp.asarray(dangling),
        jnp.asarray(0.1, jnp.float32),
    )
    jaxpr = jax.make_jaxpr(
        lambda s, d, wt, t, pp, dg, a: power_step_coo(s, d, wt, t, pp, dg, a, n=g.n)
    )(*args)
    lowered = converge_sparse.lower(
        *args[:6], n=g.n, alpha=args[6], tol=1e-6, max_iter=4
    ).as_text()
    return TraceCase(
        "tpu-sparse", jaxpr, dims={"edges": g.nnz, "n": g.n}, lowered_text=lowered
    )


def _trace_csr(graph) -> TraceCase:
    import jax
    import jax.numpy as jnp

    from ..ops.sparse import converge_csr, power_step_csr

    g, gs, w, dangling = _normalized(graph)
    p = g.pre_trust_vector()
    args = (
        jnp.asarray(gs.src),
        jnp.asarray(gs.row_ptr_by_dst()),
        jnp.asarray(gs.weight),
        jnp.asarray(p),
        jnp.asarray(p),
        jnp.asarray(dangling),
        jnp.asarray(0.1, jnp.float32),
    )
    jaxpr = jax.make_jaxpr(lambda *a: power_step_csr(*a))(*args)
    lowered = converge_csr.lower(
        *args[:6], alpha=args[6], tol=1e-6, max_iter=4
    ).as_text()
    return TraceCase(
        "tpu-csr", jaxpr, dims={"edges": g.nnz, "n": g.n}, lowered_text=lowered
    )


def _trace_windowed(graph) -> TraceCase:
    import jax
    import jax.numpy as jnp

    from ..ops.gather_window import (
        build_window_plan,
        converge_windowed,
        power_step_windowed,
    )

    g, gs, w, dangling = _normalized(graph)
    plan = build_window_plan(g.src, g.dst, w, n=g.n)
    # Keep the budget dimensions distinguishable: the rowsum gathers are
    # (n+1)-sized, the bridge reads seg_capacity-sized (the device
    # length of the padded segment tables, >= n_segments live runs).
    assert plan.seg_capacity != g.n + 1, "synthetic graph aliases budget dims"
    p = g.pre_trust_vector()
    args = plan.device_args() + (
        jnp.asarray(p),
        jnp.asarray(p),
        jnp.asarray(dangling),
        jnp.asarray(0.1, jnp.float32),
    )
    jaxpr = jax.make_jaxpr(
        lambda *a: power_step_windowed(
            *a,
            n_rows=plan.n_rows,
            table_entries=plan.table_entries,
            interpret=True,
        )
    )(*args)
    lowered = converge_windowed.lower(
        *args[:10],
        n_rows=plan.n_rows,
        table_entries=plan.table_entries,
        alpha=args[10],
        tol=1e-6,
        max_iter=4,
        interpret=True,
    ).as_text()
    return TraceCase(
        "tpu-windowed",
        jaxpr,
        dims={"n_segments": plan.seg_capacity, "n": g.n},
        lowered_text=lowered,
    )


def _trace_sharded_csr(graph) -> TraceCase:
    from functools import partial

    import jax
    import jax.numpy as jnp

    from ..parallel.mesh import SHARD_AXIS, default_mesh
    from ..parallel.sharded import ShardedTrustProblem, _get_runner

    mesh = default_mesh()
    prob = ShardedTrustProblem.build(graph, mesh)
    run = _get_runner(mesh, prob.n)
    args = (
        prob.src,
        prob.w,
        prob.row_ptr,
        prob.t0(),
        prob.p,
        prob.dangling,
        jnp.asarray(0.1, jnp.float32),
    )
    jaxpr = jax.make_jaxpr(partial(run, max_iter=4, tol=1e-6))(*args)
    lowered = run.lower(*args, max_iter=4, tol=1e-6).as_text()
    shard_edges = prob.src.shape[0] // mesh.shape[SHARD_AXIS]
    return TraceCase(
        "tpu-sharded:tpu-csr",
        jaxpr,
        dims={"edges": shard_edges, "n": prob.n},
        lowered_text=lowered,
    )


def _trace_sharded_windowed(graph) -> TraceCase:
    from functools import partial

    import jax
    import jax.numpy as jnp

    from ..parallel.mesh import default_mesh
    from ..parallel.sharded import ShardedWindowPlan, _get_windowed_runner

    mesh = default_mesh()
    swp = ShardedWindowPlan.build(graph, mesh)
    assert swp.s_max != swp.n + 1, "synthetic graph aliases budget dims"
    run = _get_windowed_runner(
        mesh, swp.n, swp.rows_per_shard, swp.table_entries, swp.interpret
    )
    args = (
        swp.wid,
        swp.local,
        swp.weight,
        swp.seg_end,
        swp.seg_first,
        swp.seg_perm,
        swp.dst_ptr,
        swp.t0(),
        swp.p,
        swp.dangling,
        jnp.asarray(0.1, jnp.float32),
    )
    jaxpr = jax.make_jaxpr(partial(run, max_iter=4, tol=1e-6))(*args)
    lowered = run.lower(*args, max_iter=4, tol=1e-6).as_text()
    return TraceCase(
        "tpu-sharded:tpu-windowed",
        jaxpr,
        dims={"n_segments": swp.s_max, "n": swp.n},
        lowered_text=lowered,
    )


#: Backend name -> trace recipe.  A budget with no recipe is an error
#: (the table must not claim coverage it cannot check).
TRACE_BUILDERS: dict[str, Callable[[Any], TraceCase]] = {
    "tpu-dense": _trace_dense,
    "tpu-sparse": _trace_sparse,
    "tpu-csr": _trace_csr,
    "tpu-windowed": _trace_windowed,
    "tpu-sharded:tpu-csr": _trace_sharded_csr,
    "tpu-sharded:tpu-windowed": _trace_sharded_windowed,
}


def _anchor(site: EqnSite | None) -> dict[str, Any]:
    if site is None:
        return {"file": None, "line": None}
    f, line = source_site(site.eqn)
    return {"file": f, "line": line}


def check_case(budget: KernelBudget, case: TraceCase) -> list[Finding]:
    """Evaluate one backend's budget against its traced step."""
    findings: list[Finding] = []
    jaxpr = case.jaxpr

    def err(rule: str, message: str, site: EqnSite | None = None) -> None:
        findings.append(
            Finding(
                pass_name="jaxpr",
                rule=rule,
                severity="error",
                message=message,
                backend=case.backend,
                **_anchor(site),
            )
        )

    # Gathers, excluding interpret-mode pallas bodies (not XLA gathers
    # on the real chip — the windowed resolve is Mosaic codegen there).
    gathers = collect_primitives(jaxpr, {"gather"}, exclude_under=("pallas_call",))
    random_gathers = [g for g in gathers if not g.sorted_indices]
    if len(random_gathers) > budget.max_random_gathers:
        err(
            "gather-budget",
            f"{len(random_gathers)} random gathers per step exceed the "
            f"declared budget of {budget.max_random_gathers}",
            random_gathers[-1],
        )

    for gb in budget.gather_budgets:
        size = case.dims.get(gb.dim)
        if size is None:
            err("gather-budget", f"trace reports no dimension {gb.dim!r}")
            continue
        sized = [g for g in gathers if g.out_shape[:1] == (size,)]
        sized_random = [g for g in sized if not g.sorted_indices]
        if len(sized) > gb.max_total:
            err(
                "sized-gather-budget",
                f"{len(sized)} {gb.dim}-sized gathers exceed the budget "
                f"of {gb.max_total}",
                sized[-1],
            )
        if len(sized_random) > gb.max_random:
            err(
                "random-gather-budget",
                f"{len(sized_random)} random {gb.dim}-sized gathers per "
                f"step exceed the budget of {gb.max_random} (the "
                f"single-pass bridge allows exactly one random pass)",
                sized_random[-1],
            )
        if gb.boundary_sorted:
            boundary = [
                g
                for g in sized
                if g.out_shape == (size, 2) and g.sorted_indices and g.unique_indices
            ]
            if len(boundary) != 1:
                candidates = [g for g in sized if g.out_shape == (size, 2)]
                err(
                    "boundary-sorted",
                    f"expected exactly one sorted+unique ({gb.dim}, 2) "
                    f"boundary gather (the streaming bridge read), found "
                    f"{len(boundary)}",
                    candidates[-1] if candidates else None,
                )

    # Scatters (scatter-free is the whole point of the CSR/windowed
    # formulations — TPU scatter serializes on destination indices).
    scatters = collect_primitives(
        jaxpr, SCATTER_PRIMITIVES, exclude_under=("pallas_call",)
    )
    if len(scatters) > budget.max_scatters:
        err(
            "scatter-budget",
            f"{len(scatters)} scatter ops per step exceed the declared "
            f"budget of {budget.max_scatters}",
            scatters[-1],
        )

    # f64 leaks.
    if not budget.allow_f64:
        leaks = has_f64(jaxpr)
        if leaks:
            err(
                "f64-dtype",
                f"{len(leaks)} equation(s) produce float64 inside the "
                "jit'd step (TPU f64 is emulated; keep the double-single "
                "(hi, lo) form instead)",
                leaks[0],
            )

    # Host callbacks.
    callbacks = collect_primitives(jaxpr, CALLBACK_PRIMITIVES)
    if callbacks:
        err(
            "callback-in-jit",
            f"host callback primitive {callbacks[0].primitive!r} inside "
            "the jit'd step (one host round-trip per iteration)",
            callbacks[0],
        )

    # psum count and placement.
    psums = collect_primitives(jaxpr, PSUM_PRIMITIVES)
    if len(psums) != budget.psum_count:
        err(
            "psum-count",
            f"expected exactly {budget.psum_count} psum per step, found "
            f"{len(psums)}",
            psums[-1] if psums else None,
        )
    for site in psums:
        if not site.under("shard_map"):
            err(
                "psum-outside-shard-map",
                "psum outside shard_map: the collective has no mesh axis "
                "to reduce over",
                site,
            )

    # Required structural primitives (MXU matmul, Pallas kernel, ...).
    present = {s.primitive for s in iter_eqns(jaxpr)}
    for prim in budget.require_primitives:
        if prim not in present:
            err(
                "missing-primitive",
                f"required primitive {prim!r} absent from the step (the "
                "fast path has been rewritten away)",
            )

    # Donated-argument aliasing must materialize in the lowering.
    if budget.donated_args:
        text = case.lowered_text
        if text is None:
            err(
                "donation-not-materialized",
                "budget declares donated args but the trace recipe "
                "provides no lowered computation to verify against",
            )
        else:
            marks = sum(text.count(m) for m in _DONATION_MARKS)
            if marks < len(budget.donated_args):
                err(
                    "donation-not-materialized",
                    f"{len(budget.donated_args)} donated arg(s) declared "
                    f"({', '.join(budget.donated_args)}) but only {marks} "
                    "aliasing mark(s) in the lowered computation",
                )
    return findings


def run_jaxpr_pass(
    backends: list[str] | None = None,
) -> tuple[list[Finding], dict[str, dict[str, Any]]]:
    """Trace and check every registered backend (or the given subset).

    Returns ``(findings, per-backend metadata)`` — the metadata feeds
    ANALYSIS.json (budget summary, dims, invariants_checked).
    """
    # Importing the registry imports the kernel modules, which declare
    # their budgets; the sharded module only loads lazily elsewhere.
    from .. import parallel  # noqa: F401  (namespace anchor)
    from ..parallel import sharded  # noqa: F401  (declares sharded budgets)
    from ..trust.backend import registered_backends
    from .zk_lowering import register as _register_zk

    registry = registered_backends()
    # The zk.graft proving kernels ride the default gate here: tracing
    # them is cheap (their expensive leg is compile, gated behind
    # ``--zk`` in the later passes).
    zk_names = _register_zk()
    targets = registry + zk_names if backends is None else backends
    findings: list[Finding] = []
    meta: dict[str, dict[str, Any]] = {}
    graph = _synthetic_graph()

    for name in targets:
        if name in NON_JAX_BACKENDS:
            meta[name] = {"status": "skipped", "reason": "non-jax backend"}
            findings.append(
                Finding(
                    pass_name="jaxpr",
                    rule="non-jax-backend",
                    severity="info",
                    message=f"{name} runs outside jax; no jaxpr to check",
                    backend=name,
                )
            )
            continue
        budget = KERNEL_INVARIANTS.get(name)
        if budget is None:
            meta[name] = {"status": "undeclared"}
            findings.append(
                Finding(
                    pass_name="jaxpr",
                    rule="undeclared-backend",
                    severity="error",
                    message=(
                        f"registered backend {name!r} declares no kernel "
                        "budget; add a KERNEL_INVARIANTS declaration next "
                        "to its kernel (see PERF.md §9)"
                    ),
                    backend=name,
                )
            )
            continue
        builder = TRACE_BUILDERS.get(name)
        if builder is None:
            meta[name] = {"status": "no-recipe"}
            findings.append(
                Finding(
                    pass_name="jaxpr",
                    rule="no-trace-recipe",
                    severity="error",
                    message=(
                        f"budget declared for {name!r} but the analyzer "
                        "has no trace recipe; coverage would be vacuous"
                    ),
                    backend=name,
                )
            )
            continue
        try:
            case = builder(graph)
        except Exception as exc:  # noqa: BLE001 - report, don't crash the gate
            meta[name] = {"status": "trace-failed", "error": repr(exc)}
            findings.append(
                Finding(
                    pass_name="jaxpr",
                    rule="trace-failure",
                    severity="error",
                    message=f"tracing the step failed: {exc!r}",
                    backend=name,
                )
            )
            continue
        case_findings = check_case(budget, case)
        findings.extend(case_findings)
        meta[name] = {
            "status": "checked",
            "invariants_checked": budget.invariant_count,
            "violations": len(case_findings),
            "dims": case.dims,
            "budget": {
                "max_random_gathers": budget.max_random_gathers,
                "max_scatters": budget.max_scatters,
                "psum_count": budget.psum_count,
                "require_primitives": list(budget.require_primitives),
                "donated_args": list(budget.donated_args),
                "gather_budgets": [
                    {
                        "dim": gb.dim,
                        "max_total": gb.max_total,
                        "max_random": gb.max_random,
                        "boundary_sorted": gb.boundary_sorted,
                    }
                    for gb in budget.gather_budgets
                ],
            },
        }

    # Budgets declared for names no longer in the registry rot silently.
    if backends is None:
        known = set(registry) | set(zk_names)
        for name in sorted(set(KERNEL_INVARIANTS) - known):
            findings.append(
                Finding(
                    pass_name="jaxpr",
                    rule="stale-budget",
                    severity="warning",
                    message=(
                        f"budget declared for {name!r} which is not a "
                        "registered backend"
                    ),
                    backend=name,
                )
            )
    return findings, meta


__all__ = ["TraceCase", "TRACE_BUILDERS", "check_case", "run_jaxpr_pass"]
