"""CLI: ``python -m protocol_tpu.analysis`` — run graftlint.

Exit code 0 iff no error-severity finding; writes ``ANALYSIS.json``
(CI uploads it as a build artifact).  ``--fixture`` runs one seeded
violation instead of the real tree — it must exit non-zero, which
doubles as the gate's self-check.
"""

from __future__ import annotations

import argparse
import os
import sys


def _ensure_cpu_mesh() -> None:
    """Force the 8-device virtual CPU mesh before jax's backend
    initializes (same doctrine as tests/conftest.py): the sharded
    composites trace under a real Mesh without TPU hardware."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m protocol_tpu.analysis",
        description="graftlint: jaxpr/AST invariant analyzer for the trust backends",
    )
    parser.add_argument(
        "--output",
        default="ANALYSIS.json",
        help="machine-readable report path (default: %(default)s)",
    )
    parser.add_argument(
        "--pass",
        dest="passes",
        choices=("all", "jaxpr", "ast", "concurrency", "comm", "memory",
                 "determinism"),
        default="all",
        help="which pass(es) to run (default: %(default)s)",
    )
    parser.add_argument(
        "--zk",
        action="store_true",
        help=(
            "extend the compile passes (comm/memory/determinism) to the "
            "zk.graft proving kernels; their EC compiles take minutes, so "
            "only the zk-graft CI job runs this by default (the jaxpr "
            "pass always covers them — tracing is cheap)"
        ),
    )
    parser.add_argument(
        "--fixture",
        default=None,
        help="run one seeded violation fixture instead of the real tree",
    )
    parser.add_argument(
        "--list-fixtures", action="store_true", help="list fixture names and exit"
    )
    args = parser.parse_args(argv)

    _ensure_cpu_mesh()
    if args.zk:
        # The zk leg compiles EC kernels that take tens of seconds per
        # (shape, kernel) pair on XLA:CPU; persist executables next to
        # the keygen cache (same doctrine as tests/conftest.py) so
        # repeat --zk runs pay compilation once per machine.
        import pathlib

        import jax

        cache_root = os.environ.setdefault(
            "PROTOCOL_TPU_CACHE",
            str(pathlib.Path(__file__).resolve().parents[2]
                / ".cache" / "protocol_tpu"),
        )
        jax_cache = pathlib.Path(cache_root) / "jax"
        jax_cache.mkdir(parents=True, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", str(jax_cache))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    from .report import Report

    report = Report()

    if args.list_fixtures:
        from .fixtures import FIXTURES

        for name, fixture in sorted(FIXTURES.items()):
            print(f"{name}: expects {fixture.rule}")
        return 0

    if args.fixture is not None:
        from .fixtures import FIXTURES, run_fixture

        if args.fixture not in FIXTURES:
            print(
                f"unknown fixture {args.fixture!r}; "
                f"available: {', '.join(sorted(FIXTURES))}",
                file=sys.stderr,
            )
            return 2
        report.extend(run_fixture(args.fixture))
        report.backends[f"fixture:{args.fixture}"] = {"status": "fixture"}
    else:
        if args.passes in ("all", "jaxpr"):
            from .invariants import run_jaxpr_pass

            findings, meta = run_jaxpr_pass()
            report.extend(findings)
            report.backends.update(meta)
        if args.passes in ("all", "ast"):
            from .ast_rules import run_ast_pass

            findings, n_files = run_ast_pass()
            report.extend(findings)
            report.files_scanned = n_files
        if args.passes in ("all", "concurrency"):
            from .concurrency import run_concurrency_pass

            findings, section = run_concurrency_pass()
            report.extend(findings)
            report.concurrency = section
        if args.passes in ("all", "comm"):
            from .comm import run_comm_pass

            findings, section = run_comm_pass(include_zk=args.zk)
            report.extend(findings)
            report.comm = section
        if args.passes in ("all", "memory"):
            from .memory import run_memory_pass

            findings, section = run_memory_pass(include_zk=args.zk)
            report.extend(findings)
            report.memory = section
        if args.passes in ("all", "determinism"):
            from .determinism import run_determinism_pass

            findings, section = run_determinism_pass(include_zk=args.zk)
            report.extend(findings)
            report.determinism = section

    report.write_json(args.output)
    print(report.render())
    return report.exit_code


if __name__ == "__main__":
    sys.exit(main())
