"""Recursive jaxpr traversal — the single source of truth for walking
trust-kernel jaxprs.

Grown out of the ad-hoc ``_collect_gathers`` helper that used to live in
``tests/test_windowed_pipeline.py``: every consumer (the invariant
analyzer, the gather-counting acceptance test) now shares one walker,
so "descends into pjit / while / scan / shard_map / pallas interpret
bodies" cannot drift between the test and the gate.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Iterator

import jax

#: Primitive families the invariant checks care about.
SCATTER_PRIMITIVES = frozenset(
    {"scatter", "scatter-add", "scatter-mul", "scatter-min", "scatter-max"}
)
#: ``psum2`` is the rewrite shard_map applies to ``psum`` under its
#: replication checker — the same collective on the wire.
PSUM_PRIMITIVES = frozenset({"psum", "psum2"})
CALLBACK_PRIMITIVES = frozenset(
    {"pure_callback", "io_callback", "debug_callback", "callback"}
)


@dataclass(frozen=True)
class EqnSite:
    """One equation plus the primitive path enclosing it (outermost
    first) — e.g. ``("pjit", "while", "shard_map")``."""

    eqn: Any  # jax.core.JaxprEqn
    path: tuple[str, ...]

    @property
    def primitive(self) -> str:
        return self.eqn.primitive.name

    def under(self, primitive: str) -> bool:
        return primitive in self.path

    @property
    def out_shape(self) -> tuple[int, ...]:
        return tuple(self.eqn.outvars[0].aval.shape)

    @property
    def sorted_indices(self) -> bool:
        return bool(self.eqn.params.get("indices_are_sorted"))

    @property
    def unique_indices(self) -> bool:
        return bool(self.eqn.params.get("unique_indices"))


def _is_jaxpr_like(x: Any) -> bool:
    return hasattr(x, "eqns") or hasattr(x, "jaxpr")


def iter_eqns(jaxpr: Any, path: tuple[str, ...] = ()) -> Iterator[EqnSite]:
    """Yield every equation of ``jaxpr`` and, recursively, of every
    sub-jaxpr reachable through equation params (pjit bodies, while
    cond/body, scan bodies, shard_map bodies, pallas interpret
    kernels), tagged with the enclosing primitive path."""
    for eqn in jaxpr.eqns:
        yield EqnSite(eqn, path)
        sub_path = path + (eqn.primitive.name,)
        for v in eqn.params.values():
            for sub in jax.tree_util.tree_leaves(v, is_leaf=_is_jaxpr_like):
                if hasattr(sub, "jaxpr"):  # ClosedJaxpr
                    yield from iter_eqns(sub.jaxpr, sub_path)
                elif hasattr(sub, "eqns"):  # raw Jaxpr
                    yield from iter_eqns(sub, sub_path)


def collect_primitives(
    jaxpr: Any,
    names: frozenset[str] | set[str],
    *,
    exclude_under: tuple[str, ...] = (),
    predicate: Callable[[EqnSite], bool] | None = None,
) -> list[EqnSite]:
    """All equation sites whose primitive is in ``names``, skipping
    sites nested under any primitive named in ``exclude_under``."""
    out = []
    for site in iter_eqns(jaxpr):
        if site.primitive not in names:
            continue
        if any(site.under(p) for p in exclude_under):
            continue
        if predicate is not None and not predicate(site):
            continue
        out.append(site)
    return out


def collect_gathers(jaxpr: Any, *, exclude_pallas: bool = False) -> list[Any]:
    """Every ``gather`` equation, descending into sub-jaxprs — the
    (generalized) successor of the test-local ``_collect_gathers``.
    Returns bare equations for drop-in use by shape/param assertions;
    ``exclude_pallas`` drops gathers inside interpret-mode
    ``pallas_call`` bodies (not XLA gathers on the real chip)."""
    exclude = ("pallas_call",) if exclude_pallas else ()
    return [s.eqn for s in collect_primitives(jaxpr, {"gather"}, exclude_under=exclude)]


def primitive_counts(jaxpr: Any) -> dict[str, int]:
    """Histogram of primitive names over the whole (recursive) jaxpr."""
    counts: dict[str, int] = {}
    for site in iter_eqns(jaxpr):
        counts[site.primitive] = counts.get(site.primitive, 0) + 1
    return counts


def source_site(eqn: Any) -> tuple[str | None, int | None]:
    """Best-effort ``(file, line)`` of the user code that traced this
    equation (jaxpr source_info; internal frames filtered by jax)."""
    try:
        from jax._src import source_info_util

        frame = source_info_util.user_frame(eqn.source_info)
        if frame is not None:
            return frame.file_name, frame.start_line
    except Exception:
        pass
    return None, None


def has_f64(jaxpr: Any) -> list[EqnSite]:
    """Equation sites producing a float64 aval anywhere in the jaxpr —
    device f64 is emulated on TPU and must never appear in a hot
    kernel (the double-single (hi, lo) machinery exists precisely to
    avoid it)."""
    out = []
    for site in iter_eqns(jaxpr):
        for v in site.eqn.outvars:
            dtype = getattr(getattr(v, "aval", None), "dtype", None)
            if dtype is not None and str(dtype) == "float64":
                out.append(site)
                break
    return out


__all__ = [
    "CALLBACK_PRIMITIVES",
    "EqnSite",
    "PSUM_PRIMITIVES",
    "SCATTER_PRIMITIVES",
    "collect_gathers",
    "collect_primitives",
    "has_f64",
    "iter_eqns",
    "primitive_counts",
    "source_site",
]
