"""graftlint pass 8 — the SPMD-lowering communication analyzer.

ROADMAP item 3 (multi-host pod scale-out) lives or dies on
communication that scales with boundary segments, not edges.  Passes
1–7 pin the jaxpr and the host program; this pass pins the layer in
between that nothing else sees: what the SPMD partitioner actually
emits when it compiles the sharded step.  A replicated-operand
rebroadcast, a surprise all-gather, or a silently dropped donation
alias would pass every existing gate and only surface as a wall at pod
scale — exactly the class of bug pass 1 closed for single-device
kernels.

- :mod:`lowering` compiles every registered backend's converge entry
  under the 8-device CPU mesh (sharded composites at two problem
  scales, E x4 vs N x2);
- :mod:`hlo_walk` parses the compiled module: collectives with replica
  groups and byte volumes from operand shapes, host round-trips, and
  the ``input_output_alias`` table;
- :mod:`checker` judges each module against the declarative
  :data:`~protocol_tpu.analysis.budget.COMM_INVARIANTS` budget declared
  next to the kernel (linear ``O(boundary + N)`` byte allowances — an
  O(E) term is structurally inexpressible *and* caught at the second
  scale), cross-checks jaxpr psums against lowered all-reduces, and
  emits the ``comm`` section of ANALYSIS.json;
- :mod:`waivers` is the enumerated, stale-tested suppression table
  (pass-7 doctrine; currently empty).

``tools/comm_probe.py`` is the runtime counterpart: a 2-process
``jax.distributed`` CPU smoke that runs one sharded converge and
asserts the measured collective structure is a subset of these static
budgets — the first executable artifact of the multi-host path.
"""

from __future__ import annotations

from .checker import check_comm_case, run_comm_pass
from .hlo_walk import CollectiveOp, HostCall, ModuleComm, parse_module
from .lowering import COMM_BUILDERS, COMM_SCALES, CommCase, build_cases
from .waivers import COMM_WAIVERS

__all__ = [
    "COMM_BUILDERS",
    "COMM_SCALES",
    "COMM_WAIVERS",
    "CollectiveOp",
    "CommCase",
    "HostCall",
    "ModuleComm",
    "build_cases",
    "check_comm_case",
    "parse_module",
    "run_comm_pass",
]
