"""Per-backend lowering recipes for pass 8.

Each recipe builds the backend's real converge entry point on a
synthetic graph, lowers it through the real jit path, **compiles** it
under the 8-device CPU mesh (the SPMD partitioner only runs at
compile), and returns the module text plus the context needed to judge
it: the problem dims the byte budget is a function of, the entry-point
argument names (so ``donated_args`` resolve to parameter numbers in
the ``input_output_alias`` table), and the jaxpr-level psum count for
the lowering cross-check.

Scales: the sharded composites — the only backends whose lowering can
legally contain collectives — are compiled at **two** scales where the
edge count grows 4x but N only 2x, so a byte volume that follows E
breaks the (linear in N/S) budget at the second scale no matter how
the constants were padded.  Single-device backends compile once: their
budget is zero collectives at any scale, so a second compile proves
nothing and the (Pallas-interpret) windowed compile is the analyzer's
dominant cost.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable

from ..jaxpr_walk import PSUM_PRIMITIVES, collect_primitives

#: (n_peers, n_edges) per scale: E x4, N x2 between the two.
COMM_SCALES: tuple[tuple[int, int], ...] = ((1024, 4096), (2048, 16384))

#: Shard count of the analysis mesh (tests/conftest.py doctrine).
N_SHARDS = 8


@dataclass
class CommCase:
    """One backend at one scale: the compiled module plus its context."""

    backend: str
    #: Budget dimensions: n, edges, n_shards, n_segments where the
    #: backend has a segment table, and n_rows where it has a windowed
    #: plan (per-shard vreg-rows — the pass-12 resident dimension).
    dims: dict[str, int]
    #: ``compiled.as_text()`` of the converge entry point.
    module_text: str
    #: Entry-point argument names, parameter order (donation mapping).
    arg_names: tuple[str, ...]
    #: psum/psum2 count in the traced jaxpr of the same entry point.
    jaxpr_psums: int = 0
    #: Buffer-assignment view of the same executable (pass 12):
    #: ``compiled.memory_analysis()`` per-device byte totals, or None
    #: when the runtime exposes no memory analysis — the memory checker
    #: then falls back to the conservative live-range walk over
    #: ``module_text``.
    mem: dict[str, int] | None = None
    #: Free-form per-scale metadata for ANALYSIS.json.
    meta: dict[str, Any] = field(default_factory=dict)


def _mem_stats(compiled: Any) -> dict[str, int] | None:
    """Per-device buffer-assignment totals of one executable, or None
    when the backend has no ``memory_analysis`` (older runtimes)."""
    try:
        ma = compiled.memory_analysis()
    except Exception:  # noqa: BLE001 - absence degrades to the HLO walk
        return None
    if ma is None:
        return None
    try:
        return {
            "argument_bytes": int(ma.argument_size_in_bytes),
            "output_bytes": int(ma.output_size_in_bytes),
            "alias_bytes": int(ma.alias_size_in_bytes),
            "temp_bytes": int(ma.temp_size_in_bytes),
        }
    except AttributeError:
        return None


def _graph(n: int, e: int):
    import numpy as np

    from ...models.graphs import scale_free
    from ...trust.graph import TrustGraph

    g = scale_free(n, e, seed=2)
    keep = ~np.isin(g.src, np.asarray([0, 17, n - 1], dtype=np.int32))
    return TrustGraph(g.n, g.src[keep], g.dst[keep], g.weight[keep], g.pre_trusted)


def _normalized(graph):
    import numpy as np

    from ...trust.graph import TrustGraph

    g = graph.drop_self_edges()
    w, dangling = g.row_normalized()
    gs = TrustGraph(g.n, g.src, g.dst, w, g.pre_trusted).sorted_by_dst()
    return g, gs, w, dangling.astype(np.float32)


def _jaxpr_psums(jaxpr: Any) -> int:
    return len(collect_primitives(jaxpr, PSUM_PRIMITIVES))


def _lower_dense(n: int, e: int) -> CommCase:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ...ops.dense import converge_dense

    rng = np.random.default_rng(0)
    size = 64
    m = rng.random((size, size)).astype(np.float32)
    m /= m.sum(axis=0, keepdims=True)
    t = jnp.asarray(np.full(size, 1.0 / size, np.float32))
    m = jnp.asarray(m)
    compiled = converge_dense.lower(m, t, 4).compile()
    jaxpr = jax.make_jaxpr(lambda mm, tt: converge_dense(mm, tt, 4))(m, t)
    return CommCase(
        backend="tpu-dense",
        dims={"n": size, "edges": size * size, "n_shards": 1},
        module_text=compiled.as_text(),
        arg_names=("ops_t", "s0"),
        jaxpr_psums=_jaxpr_psums(jaxpr),
        mem=_mem_stats(compiled),
    )


def _lower_sparse(n: int, e: int) -> CommCase:
    import jax
    import jax.numpy as jnp

    from ...ops.sparse import converge_sparse

    g, gs, w, dangling = _normalized(_graph(n, e))
    p = g.pre_trust_vector()
    args = (
        jnp.asarray(gs.src),
        jnp.asarray(gs.dst),
        jnp.asarray(gs.weight),
        jnp.asarray(p),
        jnp.asarray(p),
        jnp.asarray(dangling),
    )
    kw = dict(n=g.n, alpha=jnp.asarray(0.1, jnp.float32), tol=1e-6, max_iter=4)
    compiled = converge_sparse.lower(*args, **kw).compile()
    jaxpr = jax.make_jaxpr(
        lambda *a: converge_sparse(*a, **kw), static_argnums=()
    )(*args)
    return CommCase(
        backend="tpu-sparse",
        dims={"n": g.n, "edges": g.nnz, "n_shards": 1},
        module_text=compiled.as_text(),
        arg_names=("src", "dst", "w", "t0", "p", "dangling"),
        jaxpr_psums=_jaxpr_psums(jaxpr),
        mem=_mem_stats(compiled),
    )


def _lower_csr(n: int, e: int) -> CommCase:
    import jax
    import jax.numpy as jnp

    from ...ops.sparse import converge_csr

    g, gs, w, dangling = _normalized(_graph(n, e))
    p = g.pre_trust_vector()
    args = (
        jnp.asarray(gs.src),
        jnp.asarray(gs.row_ptr_by_dst()),
        jnp.asarray(gs.weight),
        jnp.asarray(p),
        jnp.asarray(p),
        jnp.asarray(dangling),
    )
    kw = dict(alpha=jnp.asarray(0.1, jnp.float32), tol=1e-6, max_iter=4)
    compiled = converge_csr.lower(*args, **kw).compile()
    jaxpr = jax.make_jaxpr(lambda *a: converge_csr(*a, **kw))(*args)
    return CommCase(
        backend="tpu-csr",
        dims={"n": g.n, "edges": g.nnz, "n_shards": 1},
        module_text=compiled.as_text(),
        arg_names=("src", "row_ptr", "w", "t0", "p", "dangling"),
        jaxpr_psums=_jaxpr_psums(jaxpr),
        mem=_mem_stats(compiled),
    )


def _lower_windowed(n: int, e: int) -> CommCase:
    import jax
    import jax.numpy as jnp

    from ...ops.gather_window import build_window_plan, converge_windowed

    g, gs, w, dangling = _normalized(_graph(n, e))
    plan = build_window_plan(g.src, g.dst, w, n=g.n)
    p = g.pre_trust_vector()
    args = plan.device_args() + (
        jnp.asarray(p),
        jnp.asarray(p),
        jnp.asarray(dangling),
    )
    kw = dict(
        n_rows=plan.n_rows,
        table_entries=plan.table_entries,
        alpha=jnp.asarray(0.1, jnp.float32),
        tol=1e-6,
        max_iter=4,
        interpret=True,
    )
    compiled = converge_windowed.lower(*args, **kw).compile()
    jaxpr = jax.make_jaxpr(lambda *a: converge_windowed(*a, **kw))(*args)
    return CommCase(
        backend="tpu-windowed",
        dims={
            "n": g.n,
            "edges": g.nnz,
            "n_segments": plan.seg_capacity,
            "n_rows": plan.n_rows,
            "n_shards": 1,
        },
        module_text=compiled.as_text(),
        arg_names=(
            "wid", "local", "weight", "seg_end", "seg_first", "seg_perm",
            "dst_ptr", "t0", "p", "dangling",
        ),
        jaxpr_psums=_jaxpr_psums(jaxpr),
        mem=_mem_stats(compiled),
    )


def _lower_sharded_csr(n: int, e: int) -> CommCase:
    from functools import partial

    import jax
    import jax.numpy as jnp

    from ...parallel.mesh import SHARD_AXIS, default_mesh
    from ...parallel.sharded import ShardedTrustProblem, _get_runner

    mesh = default_mesh()
    prob = ShardedTrustProblem.build(_graph(n, e), mesh)
    run = _get_runner(mesh, prob.n)
    args = (
        prob.src, prob.w, prob.row_ptr, prob.t0(), prob.p, prob.dangling,
        jnp.asarray(0.1, jnp.float32),
    )
    kw = dict(max_iter=4, tol=1e-6)
    compiled = run.lower(*args, **kw).compile()
    jaxpr = jax.make_jaxpr(partial(run, **kw))(*args)
    return CommCase(
        backend="tpu-sharded:tpu-csr",
        dims={
            "n": prob.n,
            "edges": int(prob.src.shape[0]),
            "n_shards": mesh.shape[SHARD_AXIS],
        },
        module_text=compiled.as_text(),
        arg_names=("src", "w", "row_ptr", "t0", "p", "dangling", "alpha"),
        jaxpr_psums=_jaxpr_psums(jaxpr),
        mem=_mem_stats(compiled),
    )


def _lower_sharded_windowed(n: int, e: int) -> CommCase:
    from functools import partial

    import jax
    import jax.numpy as jnp

    from ...parallel.mesh import SHARD_AXIS, default_mesh
    from ...parallel.sharded import ShardedWindowPlan, _get_windowed_runner

    mesh = default_mesh()
    graph = _graph(n, e)
    swp = ShardedWindowPlan.build(graph, mesh)
    run = _get_windowed_runner(
        mesh, swp.n, swp.rows_per_shard, swp.table_entries, swp.interpret
    )
    args = (
        swp.wid, swp.local, swp.weight, swp.seg_end, swp.seg_first,
        swp.seg_perm, swp.dst_ptr, swp.t0(), swp.p, swp.dangling,
        jnp.asarray(0.1, jnp.float32),
    )
    kw = dict(max_iter=4, tol=1e-6)
    compiled = run.lower(*args, **kw).compile()
    jaxpr = jax.make_jaxpr(partial(run, **kw))(*args)
    return CommCase(
        backend="tpu-sharded:tpu-windowed",
        dims={
            "n": swp.n,
            "edges": int(graph.drop_self_edges().nnz),
            "n_segments": swp.s_max,
            "n_rows": swp.rows_per_shard,
            "n_shards": mesh.shape[SHARD_AXIS],
        },
        module_text=compiled.as_text(),
        arg_names=(
            "wid", "local", "weight", "seg_end", "seg_first", "seg_perm",
            "dst_ptr", "t0", "p", "dangling", "alpha",
        ),
        jaxpr_psums=_jaxpr_psums(jaxpr),
        mem=_mem_stats(compiled),
    )


#: backend -> (recipe, compiled at both COMM_SCALES?).  Only the
#: sharded composites pay for the second scale — they are the backends
#: whose lowering may legally communicate.
COMM_BUILDERS: dict[str, tuple[Callable[[int, int], CommCase], bool]] = {
    "tpu-dense": (_lower_dense, False),
    "tpu-sparse": (_lower_sparse, False),
    "tpu-csr": (_lower_csr, False),
    "tpu-windowed": (_lower_windowed, False),
    "tpu-sharded:tpu-csr": (_lower_sharded_csr, True),
    "tpu-sharded:tpu-windowed": (_lower_sharded_windowed, True),
}


#: Per-process case memo: pass 8 and pass 12 judge the SAME executables
#: (comm walks the module text, memory the buffer assignment), so a
#: full ``--pass all`` run compiles each backend once, not twice — the
#: windowed Pallas-interpret compiles dominate the analyzer's wall
#: clock (the self-budget test).  Keyed by backend; the recipes are
#: deterministic in-process, and the synthetic graphs never change
#: under one run.
_CASE_CACHE: dict[str, list[CommCase]] = {}


def build_cases(backend: str) -> list[CommCase]:
    """Compile ``backend`` at its scale set and return one case per
    scale (memoized per process).  Raises KeyError for a backend
    without a recipe."""
    cached = _CASE_CACHE.get(backend)
    if cached is not None:
        return cached
    recipe, two_scale = COMM_BUILDERS[backend]
    scales = COMM_SCALES if two_scale else COMM_SCALES[:1]
    cases = [recipe(n, e) for n, e in scales]
    _CASE_CACHE[backend] = cases
    return cases


__all__ = ["COMM_BUILDERS", "COMM_SCALES", "CommCase", "N_SHARDS", "build_cases"]
