"""Compiled-HLO module walking — the pass-8 counterpart of
``jaxpr_walk``.

Pass 1 pins the *jaxpr*; this module reads what the SPMD partitioner
actually emitted.  ``jax``'s AOT path exposes the post-partitioning,
post-optimization HLO as text (``lowered.compile().as_text()``), and
that text is a stable, line-oriented format: one op per line with the
result type, typed operands, attributes, and — crucially — jax's
``metadata={... source_file=... source_line=...}`` breadcrumb back to
the user code that traced the op.  The walker extracts:

- every **collective** (all-reduce / all-gather / reduce-scatter /
  collective-permute / all-to-all) with its replica groups and byte
  volume computed from the operand/result shapes;
- every **host round-trip**: infeed/outfeed/send/recv ops and
  custom-calls whose target is a host callback (``xla_python_*`` /
  ``*callback*`` / ``*host*`` targets — device custom-calls like
  sort comparators are not round-trips and are ignored);
- the module-header **input_output_alias** table, where donation either
  materialized or silently died between the jaxpr and the executable.

Text parsing is deliberate: the HLO proto bindings churn across
jaxlib versions, while the dump format is the compiler's own
round-trippable syntax.  Every regex here is pinned by the seeded
fixtures (``analysis/fixtures.py``) that lower real modules through
the real jit path.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field

#: HLO op names counted as collectives (with -start/-done variants the
#: async pipeliner splits them into).
_COLLECTIVE_RE = (
    r"all-reduce|all-gather|reduce-scatter|collective-permute|all-to-all"
)

#: Bytes per element by HLO dtype prefix.
_DTYPE_BYTES = {
    "pred": 1,
    "s8": 1,
    "u8": 1,
    "s16": 2,
    "u16": 2,
    "bf16": 2,
    "f16": 2,
    "s32": 4,
    "u32": 4,
    "f32": 4,
    "s64": 8,
    "u64": 8,
    "f64": 8,
    "c64": 8,
    "c128": 16,
}

_OP_LINE = re.compile(
    r"^\s*(?:ROOT\s+)?%\S+\s*=\s*(?P<result>\([^=]*?\)|\S+)\s+"
    rf"(?P<op>(?:{_COLLECTIVE_RE})(?:-start|-done)?|infeed|outfeed|"
    r"send|send-done|recv|recv-done|custom-call)"
    r"\((?P<operands>.*?)\)(?P<attrs>.*)$"
)

_SHAPE = re.compile(r"(?P<dtype>[a-z]+\d*)\[(?P<dims>[\d,]*)\]")

_METADATA = re.compile(
    r'metadata=\{[^}]*?source_file="(?P<file>[^"]+)"'
    r"[^}]*?source_line=(?P<line>\d+)"
)
_OP_NAME = re.compile(r'op_name="(?P<op_name>[^"]+)"')
_REPLICA_GROUPS = re.compile(r"replica_groups=(?P<groups>\{[^=]*?\}\})")
_CUSTOM_TARGET = re.compile(r'custom_call_target="(?P<target>[^"]+)"')

#: custom_call_target substrings that mean "leave the device, talk to
#: the Python host" — the one-round-trip-per-iteration wall class.
_HOST_TARGET_MARKS = ("callback", "python", "host_")

#: ``input_output_alias={ {0}: (3, {}, may-alias), ... }`` — pairs of
#: (output tuple index, parameter number).  The table ends at the last
#: ``) }`` so the inner ``{}`` shape-index braces cannot truncate it.
_ALIAS_TABLE = re.compile(r"input_output_alias=\{(?P<table>.*?)\)\s*\}")
_ALIAS_PAIR = re.compile(r"\{(?P<out>[\d,\s]*)\}:\s*\((?P<param>\d+)")


def shape_bytes(typed: str) -> int:
    """Total bytes of every shape literal in ``typed`` (an HLO type or
    typed-operand string): ``f32[512]{0}`` -> 2048, tuples summed."""
    total = 0
    for m in _SHAPE.finditer(typed):
        unit = _DTYPE_BYTES.get(m.group("dtype"))
        if unit is None:
            continue
        numel = 1
        dims = m.group("dims")
        if dims:
            for d in dims.split(","):
                numel *= int(d)
        total += unit * numel
    return total


@dataclass(frozen=True)
class CollectiveOp:
    """One lowered collective with its wire-volume accounting."""

    kind: str  # normalized: "all-reduce", "all-gather", ...
    result_bytes: int
    operand_bytes: int
    replica_groups: str
    op_name: str  # jax metadata path, e.g. ".../while/body/.../psum"
    file: str | None
    line: int | None

    @property
    def bytes(self) -> int:
        """Wire volume attributed to the op: the larger of what goes in
        and what comes out (all-gather outputs dominate, all-reduce is
        symmetric) — computed from the typed operand/result shapes."""
        return max(self.result_bytes, self.operand_bytes)

    @property
    def per_iteration(self) -> bool:
        """True when the op sits inside the power-iteration while body
        (jax's op_name metadata carries the trace path)."""
        return "/while/" in self.op_name

    def to_dict(self) -> dict:
        return {
            "kind": self.kind,
            "bytes": self.bytes,
            "replica_groups": self.replica_groups,
            "per_iteration": self.per_iteration,
            "op_name": self.op_name,
            "file": self.file,
            "line": self.line,
        }


@dataclass(frozen=True)
class HostCall:
    """One host round-trip site in the compiled module."""

    op: str  # "custom-call" | "infeed" | "outfeed" | "send" | "recv"
    target: str  # custom_call_target, or "" for infeed/outfeed/send/recv
    file: str | None
    line: int | None
    #: Bytes the transfer carries — max of what goes out (operands) and
    #: what comes back (result), from the typed shapes.  Pass 12 caps
    #: this per op (``host-staging-over-cap``): an O(E) staging copy
    #: outside plan build is a finding even where a round-trip per se
    #: is waived.
    bytes: int = 0

    def to_dict(self) -> dict:
        return {"op": self.op, "target": self.target, "file": self.file,
                "line": self.line, "bytes": self.bytes}


@dataclass
class ModuleComm:
    """Everything pass 8 reads out of one compiled module."""

    collectives: list[CollectiveOp] = field(default_factory=list)
    host_calls: list[HostCall] = field(default_factory=list)
    #: output tuple index -> donated parameter number.
    aliases: dict[int, int] = field(default_factory=dict)

    def kind_counts(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for op in self.collectives:
            out[op.kind] = out.get(op.kind, 0) + 1
        return out

    def total_bytes(self, per_iteration_only: bool = False) -> int:
        return sum(
            op.bytes
            for op in self.collectives
            if op.per_iteration or not per_iteration_only
        )

    def aliased_params(self) -> set[int]:
        return set(self.aliases.values())


def replica_group_sizes(groups: str) -> list[int]:
    """Sizes of each replica group in an HLO ``replica_groups`` literal:
    ``{{0,1,2,3},{4,5,6,7}}`` -> ``[4, 4]``; the empty literal (``{}``
    or missing — HLO shorthand for "all devices in one group") ->
    ``[]``.  Pass 8's multi-host coverage rule reads this to assert the
    boundary-completing psum spans the whole pod mesh rather than a
    per-host subgroup."""
    sizes = []
    for inner in re.findall(r"\{([\d,\s]*)\}", groups):
        ids = [tok for tok in inner.replace(",", " ").split() if tok]
        if ids:
            sizes.append(len(ids))
    return sizes


def _normalize_kind(op: str) -> str:
    """Fold the async ``-start``/``-done`` split back to one op (count
    the start, drop the done — one wire transfer either way)."""
    return op[: -len("-start")] if op.endswith("-start") else op


def parse_module(text: str) -> ModuleComm:
    """Walk one compiled module dump (``compiled.as_text()``)."""
    mod = ModuleComm()
    header = text.split("\n", 1)[0]
    alias = _ALIAS_TABLE.search(header)
    if alias:
        for pair in _ALIAS_PAIR.finditer(alias.group("table")):
            out_idx = int((pair.group("out").strip() or "0").split(",")[0])
            mod.aliases[out_idx] = int(pair.group("param"))

    for line in text.splitlines():
        m = _OP_LINE.match(line)
        if m is None:
            continue
        op = m.group("op")
        attrs = m.group("attrs")
        meta = _METADATA.search(attrs)
        file = meta.group("file") if meta else None
        lineno = int(meta.group("line")) if meta else None
        if op.endswith("-done"):
            continue  # the matching -start carries the transfer
        volume = max(shape_bytes(m.group("result")), shape_bytes(m.group("operands")))
        if op == "custom-call":
            target = _CUSTOM_TARGET.search(attrs)
            name = target.group("target") if target else ""
            if any(mark in name.lower() for mark in _HOST_TARGET_MARKS):
                mod.host_calls.append(
                    HostCall("custom-call", name, file, lineno, bytes=volume)
                )
            continue
        if op in ("infeed", "outfeed", "send", "recv"):
            mod.host_calls.append(HostCall(op, "", file, lineno, bytes=volume))
            continue
        groups = _REPLICA_GROUPS.search(attrs)
        op_name = _OP_NAME.search(attrs)
        mod.collectives.append(
            CollectiveOp(
                kind=_normalize_kind(op),
                result_bytes=shape_bytes(m.group("result")),
                operand_bytes=shape_bytes(m.group("operands")),
                replica_groups=groups.group("groups") if groups else "",
                op_name=op_name.group("op_name") if op_name else "",
                file=file,
                line=lineno,
            )
        )
    return mod


__all__ = [
    "CollectiveOp",
    "HostCall",
    "ModuleComm",
    "parse_module",
    "replica_group_sizes",
    "shape_bytes",
]
