"""Pass 8 — the SPMD-lowering communication analyzer.

Pass 1 proved the *jaxpr* does one psum under shard_map; this pass
proves the **partitioner kept that promise**.  For every registered
backend it compiles the converge entry point under the 8-device CPU
mesh (``comm.lowering``), walks the compiled module (``comm.hlo_walk``),
and checks the declarative
:data:`~protocol_tpu.analysis.budget.COMM_INVARIANTS` budget the
kernel module declared:

- **collective-kind** — a collective kind the budget does not allow at
  all (the classic partitioner surprise: a replicated-operand
  rebroadcast materializing as an all-gather);
- **collective-count** — more ops of an allowed kind than budgeted;
- **comm-bytes-budget** — per-iteration collective bytes (computed
  from operand/result shapes) exceed the linear ``O(boundary + N)``
  budget, evaluated at every compiled scale — the sharded composites
  compile at two scales where E grows 4x vs N's 2x, so an O(E) term
  cannot hide in constants;
- **host-round-trip** — infeed/outfeed/send/recv or a host-callback
  custom-call in the compiled module;
- **alias-dropped** — a declared donated argument missing from the
  compiled module's ``input_output_alias`` table (donation must
  survive lowering, not just appear in the jaxpr);
- **psum-lowering-mismatch** — jaxpr-level psum count != lowered
  all-reduce count (either direction is a surprise: DCE'd collectives
  mean the jaxpr lies about the wire, extra all-reduces mean the
  partitioner invented traffic).

Registry housekeeping mirrors pass 1: a registered jax backend without
a COMM_INVARIANTS entry is an error (``undeclared-comm-budget``), a
budget without a lowering recipe is an error (``no-comm-recipe``), and
a budget for an unregistered name is a warning (``stale-comm-budget``).
"""

from __future__ import annotations

from typing import Any

from ..budget import COLLECTIVE_KINDS, COMM_INVARIANTS, NON_JAX_BACKENDS, CommBudget
from ..report import Finding
from .hlo_walk import parse_module, replica_group_sizes
from .lowering import COMM_BUILDERS, CommCase, build_cases
from .waivers import COMM_WAIVERS


def _finding(rule: str, message: str, backend: str | None = None,
             file: str | None = None, line: int | None = None,
             severity: str = "error") -> Finding:
    return Finding(
        pass_name="comm", rule=rule, severity=severity, message=message,
        backend=backend, file=file, line=line,
    )


def check_comm_case(budget: CommBudget, case: CommCase) -> tuple[list[Finding], dict]:
    """Evaluate one backend-at-one-scale module against its budget.

    Returns ``(findings, scale record)`` — the record feeds the
    per-backend ``comm`` section of ANALYSIS.json.
    """
    findings: list[Finding] = []
    mod = parse_module(case.module_text)
    dims = case.dims
    scale = f"N={dims.get('n')}/E={dims.get('edges')}"

    # Collective kinds and counts.
    counts = mod.kind_counts()
    for kind, count in sorted(counts.items()):
        site = next(op for op in mod.collectives if op.kind == kind)
        if kind not in COLLECTIVE_KINDS:
            # -start/-done splits are normalized; anything else here is
            # a walker gap, surface it loudly rather than miscount.
            findings.append(_finding(
                "collective-kind",
                f"unrecognized collective {kind!r} in the lowering",
                case.backend, site.file, site.line,
            ))
            continue
        allowed = budget.allowed_count(kind)
        if allowed == 0:
            findings.append(_finding(
                "collective-kind",
                f"lowering contains {count} {kind} op(s) at {scale} but the "
                f"comm budget allows none — the partitioner introduced "
                f"communication the jaxpr never asked for",
                case.backend, site.file, site.line,
            ))
        elif count > allowed:
            findings.append(_finding(
                "collective-count",
                f"{count} {kind} op(s) at {scale} exceed the declared "
                f"budget of {allowed}",
                case.backend, site.file, site.line,
            ))

    # Replica-group coverage (pod doctrine): every collective must span
    # the whole shard mesh in ONE group.  A per-host subgroup on the
    # boundary-completing psum leaves rows whose runs straddle hosts
    # incomplete — wrong scores, not just wrong bytes — and empty
    # groups (HLO's "all devices" shorthand) pass.
    if budget.require_full_replica_group:
        n_shards = dims.get("n_shards", 1)
        for op in mod.collectives:
            sizes = replica_group_sizes(op.replica_groups)
            if sizes and (len(sizes) != 1 or sizes[0] != n_shards):
                findings.append(_finding(
                    "replica-group-coverage",
                    f"{op.kind} at {scale} partitions the mesh into "
                    f"groups of {sizes} instead of one {n_shards}-device "
                    f"group (replica_groups={op.replica_groups}) — a "
                    f"subgroup reduce completes only a subset of the "
                    f"boundary rows",
                    case.backend, op.file, op.line,
                ))

    # Byte budget, per-iteration ops only (one-time resharding outside
    # the while loop is judged by kind/count above).
    measured = mod.total_bytes(per_iteration_only=True)
    allowed_bytes = budget.max_bytes(
        dims.get("n", 0), dims.get("n_segments", 0), dims.get("n_shards", 1)
    )
    if measured > allowed_bytes:
        per_iter = [op for op in mod.collectives if op.per_iteration]
        site = per_iter[-1] if per_iter else None
        findings.append(_finding(
            "comm-bytes-budget",
            f"per-iteration collective volume {measured} B at {scale} "
            f"exceeds the O(boundary + N) budget of {allowed_bytes:.0f} B "
            f"(bytes_n={budget.bytes_n}, bytes_segments="
            f"{budget.bytes_segments}, bytes_shards={budget.bytes_shards}, "
            f"bytes_const={budget.bytes_const})",
            case.backend,
            site.file if site else None,
            site.line if site else None,
        ))

    # Host round-trips.
    if len(mod.host_calls) > budget.max_host_round_trips:
        site = mod.host_calls[-1]
        findings.append(_finding(
            "host-round-trip",
            f"{len(mod.host_calls)} host round-trip(s) in the compiled "
            f"module (budget {budget.max_host_round_trips}): "
            + ", ".join(h.target or h.op for h in mod.host_calls),
            case.backend, site.file, site.line,
        ))

    # Donation must survive into the executable's alias table.
    aliased = mod.aliased_params()
    for name in budget.donated_args:
        if name not in case.arg_names:
            findings.append(_finding(
                "alias-dropped",
                f"budget donates {name!r} but the lowering recipe reports "
                f"no such argument (recipe/budget drift)",
                case.backend,
            ))
            continue
        param = case.arg_names.index(name)
        if param not in aliased:
            findings.append(_finding(
                "alias-dropped",
                f"donated argument {name!r} (parameter {param}) is absent "
                f"from input_output_alias={sorted(mod.aliases.items())} — "
                f"the donation died between the jaxpr and the executable",
                case.backend,
            ))

    # jaxpr psum count vs lowered all-reduce count.
    lowered_ar = counts.get("all-reduce", 0)
    if lowered_ar != case.jaxpr_psums:
        ars = [op for op in mod.collectives if op.kind == "all-reduce"]
        site = ars[-1] if ars else None
        findings.append(_finding(
            "psum-lowering-mismatch",
            f"jaxpr has {case.jaxpr_psums} psum(s) but the compiled module "
            f"has {lowered_ar} all-reduce(s) at {scale} — the partitioner "
            f"changed the collective structure",
            case.backend,
            site.file if site else None,
            site.line if site else None,
        ))

    record = {
        "scale": scale,
        "dims": dims,
        "collectives": [op.to_dict() for op in mod.collectives],
        "bytes_per_iter": measured,
        "budget_bytes": allowed_bytes,
        "host_round_trips": [h.to_dict() for h in mod.host_calls],
        "input_output_alias": {str(k): v for k, v in sorted(mod.aliases.items())},
        "jaxpr_psums": case.jaxpr_psums,
        "lowered_all_reduces": lowered_ar,
        "violations": len(findings),
    }
    return findings, record


def _apply_waivers(findings: list[Finding]) -> tuple[list[Finding], list[dict], list[dict]]:
    """Split findings into (live, waived records, stale records) using
    the enumerated COMM_WAIVERS table — pass-7 doctrine."""
    live: list[Finding] = []
    waived: list[dict] = []
    matched: set[int] = set()
    for f in findings:
        hit = next(
            (
                (i, w)
                for i, w in enumerate(COMM_WAIVERS)
                if w.matches(f.rule, f.file or "", f.message)
            ),
            None,
        )
        if hit is None:
            live.append(f)
        else:
            matched.add(hit[0])
            waived.append({
                "rule": f.rule, "file": f.file, "line": f.line,
                "symbol": hit[1].symbol, "reason": hit[1].reason,
            })
    stale = [
        {"symbol": w.symbol, "rule": w.rule, "reason": w.reason}
        for i, w in enumerate(COMM_WAIVERS)
        if i not in matched
    ]
    return live, waived, stale


def run_comm_pass(
    backends: list[str] | None = None,
    *,
    include_zk: bool = False,
) -> tuple[list[Finding], dict[str, Any]]:
    """Compile and check every registered backend (or the subset).

    ``include_zk`` extends the default run to the zk.graft proving
    kernels (``graftlint --zk`` / the zk-graft CI job) — their EC
    compiles are too slow for the analyzer's default self-budget.

    Returns ``(findings, comm section)`` for ANALYSIS.json.
    """
    # Importing the registry imports the kernel modules, which declare
    # their comm budgets next to their kernel budgets.
    from ...parallel import sharded  # noqa: F401  (declares sharded budgets)
    from ...trust.backend import registered_backends
    from ..zk_lowering import register as _register_zk, zk_kernel_names

    registry = registered_backends()
    zk_names = zk_kernel_names()
    if include_zk or (backends and set(backends) & set(zk_names)):
        _register_zk()
    if backends is None:
        targets = registry + zk_names if include_zk else registry
    else:
        targets = backends
    findings: list[Finding] = []
    section: dict[str, Any] = {"backends": {}}

    for name in targets:
        if name in NON_JAX_BACKENDS:
            section["backends"][name] = {
                "status": "skipped", "reason": "non-jax backend",
            }
            continue
        budget = COMM_INVARIANTS.get(name)
        if budget is None:
            section["backends"][name] = {"status": "undeclared"}
            findings.append(_finding(
                "undeclared-comm-budget",
                f"registered backend {name!r} declares no comm budget; add "
                "a COMM_INVARIANTS declaration next to its kernel (the "
                "same policy as kernel budgets, PERF.md §15)",
                name,
            ))
            continue
        if name not in COMM_BUILDERS:
            section["backends"][name] = {"status": "no-recipe"}
            findings.append(_finding(
                "no-comm-recipe",
                f"comm budget declared for {name!r} but the analyzer has "
                "no lowering recipe; coverage would be vacuous",
                name,
            ))
            continue
        try:
            cases = build_cases(name)
        except Exception as exc:  # noqa: BLE001 - report, don't crash the gate
            section["backends"][name] = {
                "status": "lowering-failed", "error": repr(exc),
            }
            findings.append(_finding(
                "comm-lowering-failure",
                f"compiling the step failed: {exc!r}",
                name,
            ))
            continue
        records = []
        n_violations = 0
        for case in cases:
            case_findings, record = check_comm_case(budget, case)
            findings.extend(case_findings)
            n_violations += len(case_findings)
            records.append(record)
        section["backends"][name] = {
            "status": "checked",
            "scales": records,
            "violations": n_violations,
            "budget": {
                "collectives": [
                    {"kind": cb.kind, "max_count": cb.max_count}
                    for cb in budget.collectives
                ],
                "bytes_n": budget.bytes_n,
                "bytes_segments": budget.bytes_segments,
                "bytes_shards": budget.bytes_shards,
                "bytes_const": budget.bytes_const,
                "max_host_round_trips": budget.max_host_round_trips,
                "require_full_replica_group": budget.require_full_replica_group,
                "donated_args": list(budget.donated_args),
                "notes": budget.notes,
            },
        }

    # Budgets for names no longer in the registry rot silently.  The zk
    # kernel names are live even when this run excludes them (their
    # budgets register whenever the graft modules import in-process).
    if backends is None:
        known = set(registry) | set(zk_names)
        for name in sorted(set(COMM_INVARIANTS) - known):
            findings.append(_finding(
                "stale-comm-budget",
                f"comm budget declared for {name!r} which is not a "
                "registered backend",
                name, severity="warning",
            ))

    live, waived, stale = _apply_waivers(findings)
    for entry in stale:
        # A dead waiver is itself a gate failure — pass-7 doctrine,
        # enforced in the default full run for every waiver table.
        live.append(_finding(
            "stale-waiver",
            f"comm waiver {entry['symbol']!r} ({entry['rule']}) matches no "
            "live finding; a fixed lowering must take its waiver with it",
            None,
        ))
    section["waived"] = waived
    section["stale_waivers"] = stale
    return live, section


__all__ = ["check_comm_case", "run_comm_pass"]
