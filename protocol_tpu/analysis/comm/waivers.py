"""Explicit pass-8 waivers — same doctrine as the pass-7 table
(``analysis/concurrency/waivers.py``): every suppression is enumerated
with its rationale, emitted into ANALYSIS.json, and **stale-tested** in
the default full run — a waiver that no longer matches a live finding
is itself an error (``stale-waiver``), so a fixed lowering takes its
waiver with it.

The table starts empty on purpose: the lowered comm structure of every
registered backend currently fits its declared budget with no
exceptions, and the first waiver added here should arrive with the
partitioner surprise it documents.
"""

from __future__ import annotations

from ..concurrency.waivers import Waiver

#: (rule, file substring, message substring) -> rationale — see
#: :class:`~protocol_tpu.analysis.concurrency.waivers.Waiver`.
COMM_WAIVERS: tuple[Waiver, ...] = ()

__all__ = ["COMM_WAIVERS"]
