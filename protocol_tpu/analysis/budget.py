"""Declarative per-backend kernel budgets — the ``KERNEL_INVARIANTS`` table.

Every trust backend's fast path rests on invariants of its *lowered*
computation that neither the type system nor the test assertions see:
how many random gathers one power step performs, that the boundary read
streams (``indices_are_sorted``), that nothing upcasts to f64 or calls
back to the host inside the jit'd loop.  "Analysis of Power Iteration
Algorithm with Partially Observed Matrix-vector Products" (PAPERS.md)
makes the underlying point precise: the convergence claims hold for a
specific per-iteration access pattern, so the access pattern is part of
the kernel's contract.

The budgets are *declared next to the kernels they pin* — each kernel
module calls :func:`declare` at import time — and *checked* by
``protocol_tpu.analysis.invariants``, which traces each backend's step
function to a closed jaxpr and walks it.  Adding a backend to the
``trust/backend.py`` registry without declaring its budget is itself a
lint error (``undeclared-backend``), so every future backend inherits
the gate for free.

This module is a dependency leaf: the kernel modules import it, so it
must not import jax, numpy, or anything else from ``protocol_tpu``.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class GatherBudget:
    """Budget for the gathers of one named size class.

    ``dim`` names a dimension the trace recipe reports (e.g.
    ``n_segments``); every gather whose leading output dimension equals
    that size is counted against this budget.  ``boundary_sorted``
    additionally requires exactly one ``(dim, 2)``-shaped gather marked
    ``indices_are_sorted`` + ``unique_indices`` — the streaming
    two-lane boundary read of the single-pass bridge (PERF.md §8).
    """

    dim: str
    max_total: int
    max_random: int
    boundary_sorted: bool = False


@dataclass(frozen=True)
class KernelBudget:
    """The per-backend invariant contract checked by pass 1.

    Counting conventions: gathers/scatters inside a ``pallas_call``
    body are excluded (interpret-mode bodies re-express the Mosaic
    kernel as XLA ops; on the real chip they are not XLA gathers), and
    a "random" gather is one not marked ``indices_are_sorted``.
    """

    backend: str
    #: Max gathers without ``indices_are_sorted`` per step (all sizes).
    max_random_gathers: int
    #: Max scatter-family ops per step (``scatter``/``scatter-add``/...).
    max_scatters: int = 0
    #: f64 avals permitted anywhere in the step jaxpr.
    allow_f64: bool = False
    #: Exact number of ``psum``/``psum2`` collectives per step; any
    #: psum present must sit under a ``shard_map``.
    psum_count: int = 0
    #: Primitives that must appear somewhere in the step (e.g.
    #: ``dot_general`` for the MXU path, ``pallas_call`` for windowed).
    require_primitives: tuple[str, ...] = ()
    #: Size-classed gather budgets (see :class:`GatherBudget`).
    gather_budgets: tuple[GatherBudget, ...] = ()
    #: Converge-function arguments declared donated; the analyzer
    #: verifies the aliasing materialized in the lowered computation.
    donated_args: tuple[str, ...] = ()
    #: Free-form rationale recorded in ANALYSIS.json.
    notes: str = ""

    @property
    def invariant_count(self) -> int:
        """How many distinct invariants checking this budget evaluates
        (the acceptance floor is >= 3 per registered backend)."""
        n = 4  # random-gather, scatter, f64, callback checks always run
        n += 1  # psum count/placement is always asserted (incl. == 0)
        n += len(self.require_primitives)
        for gb in self.gather_budgets:
            n += 2 + (1 if gb.boundary_sorted else 0)
        if self.donated_args:
            n += 1
        return n


#: Backends that never enter jax — exempt from jaxpr analysis.
NON_JAX_BACKENDS = frozenset({"native-cpu"})

#: The table: backend name -> declared budget.  Populated by kernel
#: modules at import; read by ``protocol_tpu.analysis.invariants`` and
#: cross-checked against the ``trust/backend.py`` registry.
KERNEL_INVARIANTS: dict[str, KernelBudget] = {}


def declare(budget: KernelBudget) -> KernelBudget:
    """Register a kernel budget (idempotent per backend name; kernel
    modules call this at import time, next to the kernel they pin)."""
    KERNEL_INVARIANTS[budget.backend] = budget
    return budget


# ---------------------------------------------------------------------------
# Communication budgets — the ``COMM_INVARIANTS`` table (graftlint pass 8)
# ---------------------------------------------------------------------------

#: Collective kinds the SPMD partitioner can emit, as spelled in HLO.
COLLECTIVE_KINDS = (
    "all-reduce",
    "all-gather",
    "reduce-scatter",
    "collective-permute",
    "all-to-all",
)


@dataclass(frozen=True)
class CollectiveBudget:
    """Allowance for one collective kind in the lowered module.

    ``max_count`` caps the number of ops of this kind anywhere in the
    compiled module (the power-iteration body runs once per step, so a
    static op in the loop body IS the per-iteration count); a kind with
    no :class:`CollectiveBudget` entry is forbidden outright — a
    partitioner-introduced all-gather must be declared, never silent.
    """

    kind: str  # one of COLLECTIVE_KINDS
    max_count: int


@dataclass(frozen=True)
class CommBudget:
    """Per-backend communication contract checked by pass 8 against the
    *compiled* (SPMD-partitioned) module, not the jaxpr.

    The byte budget is deliberately declarative-linear: the allowance
    for per-iteration collective traffic is ``bytes_n * N +
    bytes_segments * n_segments + bytes_shards * n_shards +
    bytes_const``.  An O(E) term is structurally inexpressible, and the
    analyzer still *evaluates* the budget against measured bytes at two
    problem scales where E grows 4x while N grows 2x — so an O(E)
    lowering cannot hide inside a padded constant either (the sparse
    power-method scaling argument of arXiv:2105.03874: communication
    must follow boundary + N, never edges).
    """

    backend: str
    #: Allowed collective kinds and per-module op-count caps; kinds
    #: absent from this tuple are forbidden in the lowering.
    collectives: tuple[CollectiveBudget, ...] = ()
    #: Linear coefficients of the per-iteration collective byte budget.
    bytes_n: float = 0.0
    bytes_segments: float = 0.0
    bytes_shards: float = 0.0
    bytes_const: float = 0.0
    #: Host round-trips (infeed/outfeed/send/recv/host-callback
    #: custom-calls) permitted in the compiled module.
    max_host_round_trips: int = 0
    #: Require every lowered collective's replica groups to form ONE
    #: group spanning all ``n_shards`` devices (pod doctrine: the
    #: boundary-completing psum must cover the whole mesh — a
    #: partitioner that splits it into per-host subgroups leaves rows
    #: whose runs straddle hosts incomplete, a silent wrong-result,
    #: and a hierarchical reduce that *re-covers* the mesh shows up as
    #: extra collectives under the count caps above).  Groups the HLO
    #: leaves empty mean "all devices" and pass.
    require_full_replica_group: bool = False
    #: Arguments whose donation must survive all the way into the
    #: compiled module's ``input_output_alias`` table (a dropped alias
    #: doubles peak HBM at the 1M-peer shape and ships silently).
    donated_args: tuple[str, ...] = ()
    #: Free-form rationale recorded in ANALYSIS.json.
    notes: str = ""

    def max_bytes(self, n: int, n_segments: int, n_shards: int) -> float:
        """Evaluate the linear byte budget at one problem scale."""
        return (
            self.bytes_n * n
            + self.bytes_segments * n_segments
            + self.bytes_shards * n_shards
            + self.bytes_const
        )

    def allowed_count(self, kind: str) -> int:
        for cb in self.collectives:
            if cb.kind == kind:
                return cb.max_count
        return 0


#: backend name -> declared comm budget.  Populated by kernel modules
#: at import (next to their KERNEL_INVARIANTS declarations); read by
#: ``protocol_tpu.analysis.comm`` and cross-checked against the
#: ``trust/backend.py`` registry — a registered jax backend without an
#: entry is an error, the same policy as kernel budgets.
COMM_INVARIANTS: dict[str, CommBudget] = {}


def declare_comm(budget: CommBudget) -> CommBudget:
    """Register a comm budget (idempotent per backend name; kernel
    modules call this at import time, next to ``declare``)."""
    COMM_INVARIANTS[budget.backend] = budget
    return budget


# ---------------------------------------------------------------------------
# Memory budgets — the ``MEM_INVARIANTS`` table (graftlint pass 12)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class MemBudget:
    """Per-backend peak-HBM contract checked by pass 12 against the
    compiled module's buffer assignment (``compiled.memory_analysis()``,
    with a conservative live-range walk over the optimized HLO as
    fallback).  All numbers are the **per-device** view — under the
    8-way analysis mesh that IS the per-shard footprint, so "per-shard
    peak scales as E/n_shards" is the formula itself, not a separate
    rule.

    The allowance decomposes into two declarative halves:

    - **resident** — the argument arrays the kernel holds for the whole
      call (edge tables, window-plan rows, segment tables, score
      vectors).  The edge term is divided by ``n_shards``: an
      accidentally replicated edge operand busts the budget by
      construction (``shard-replicated-edges``).
    - **transient** — XLA's temp arena plus unaliased outputs: the
      iteration's live working set.  It is linear in N, n_segments,
      and plan vreg-rows only — there is **no edge coefficient**, so a
      second O(E)-sized live buffer beyond the declared resident
      arrays is structurally inexpressible (``o-e-live-temporary``).
      ``transient_rows`` exists for the windowed kernels: the Pallas
      interpret-mode compile re-expresses the Mosaic kernel as XLA
      ops, and its scratch is a small multiple of the 8 KB row tables
      (on the real chip this is VMEM scratch, not HBM) — rows are a
      plan-layout dimension (1024 edge slots each), never a raw edge
      count.

    Coefficients are pinned tight: the analyzer compiles the sharded
    composites at two scales where E grows 4x vs N's 2x, and the
    acceptance test asserts the committed slack is below a 4 B/edge
    live temporary at *either* scale — the COMM_INVARIANTS pinning
    trick (PERF.md §15), applied to liveness instead of wire bytes.
    """

    backend: str
    #: Resident (argument) allowance coefficients.
    resident_edge_bytes: float = 0.0  # x E / n_shards
    resident_n: float = 0.0  # x N
    resident_segments: float = 0.0  # x n_segments (per-shard table)
    resident_rows: float = 0.0  # x plan vreg-rows (per shard)
    resident_const: float = 0.0
    #: Transient (temp arena + unaliased output) allowance — NO edge
    #: coefficient can be declared here, by construction.
    transient_n: float = 0.0
    transient_segments: float = 0.0
    transient_rows: float = 0.0
    transient_const: float = 0.0
    #: Arguments whose donation must materialize as buffer aliasing:
    #: a dropped alias shows up as a doubled f32[N] carry
    #: (``donation-peak-doubled``).  Each entry is an f32[N] seed.
    donated_args: tuple[str, ...] = ()
    #: Per-op host-transfer byte cap (``staging_n * N + staging_const``):
    #: a transfer custom-call carrying more than this — an O(E) staging
    #: copy outside plan build — is a ``host-staging-over-cap`` finding.
    staging_n: float = 0.0
    staging_const: float = 0.0
    #: Free-form rationale recorded in ANALYSIS.json.
    notes: str = ""

    def max_resident(
        self, n: int, edges: int, n_segments: int, rows: int, n_shards: int
    ) -> float:
        return (
            self.resident_edge_bytes * edges / max(n_shards, 1)
            + self.resident_n * n
            + self.resident_segments * n_segments
            + self.resident_rows * rows
            + self.resident_const
        )

    def max_transient(self, n: int, n_segments: int, rows: int) -> float:
        return (
            self.transient_n * n
            + self.transient_segments * n_segments
            + self.transient_rows * rows
            + self.transient_const
        )

    def staging_cap(self, n: int) -> float:
        return self.staging_n * n + self.staging_const


#: backend name -> declared memory budget.  Populated by kernel modules
#: at import (next to their KERNEL_INVARIANTS / COMM_INVARIANTS
#: declarations); read by ``protocol_tpu.analysis.memory`` and
#: cross-checked against the ``trust/backend.py`` registry — a
#: registered jax backend without an entry is an error, the same policy
#: as kernel and comm budgets.
MEM_INVARIANTS: dict[str, MemBudget] = {}


def declare_mem(budget: MemBudget) -> MemBudget:
    """Register a memory budget (idempotent per backend name; kernel
    modules call this at import time, next to ``declare``)."""
    MEM_INVARIANTS[budget.backend] = budget
    return budget


__all__ = [
    "COLLECTIVE_KINDS",
    "COMM_INVARIANTS",
    "CollectiveBudget",
    "CommBudget",
    "GatherBudget",
    "KernelBudget",
    "KERNEL_INVARIANTS",
    "MEM_INVARIANTS",
    "MemBudget",
    "NON_JAX_BACKENDS",
    "declare",
    "declare_comm",
    "declare_mem",
]
