"""Declarative per-backend kernel budgets — the ``KERNEL_INVARIANTS`` table.

Every trust backend's fast path rests on invariants of its *lowered*
computation that neither the type system nor the test assertions see:
how many random gathers one power step performs, that the boundary read
streams (``indices_are_sorted``), that nothing upcasts to f64 or calls
back to the host inside the jit'd loop.  "Analysis of Power Iteration
Algorithm with Partially Observed Matrix-vector Products" (PAPERS.md)
makes the underlying point precise: the convergence claims hold for a
specific per-iteration access pattern, so the access pattern is part of
the kernel's contract.

The budgets are *declared next to the kernels they pin* — each kernel
module calls :func:`declare` at import time — and *checked* by
``protocol_tpu.analysis.invariants``, which traces each backend's step
function to a closed jaxpr and walks it.  Adding a backend to the
``trust/backend.py`` registry without declaring its budget is itself a
lint error (``undeclared-backend``), so every future backend inherits
the gate for free.

This module is a dependency leaf: the kernel modules import it, so it
must not import jax, numpy, or anything else from ``protocol_tpu``.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class GatherBudget:
    """Budget for the gathers of one named size class.

    ``dim`` names a dimension the trace recipe reports (e.g.
    ``n_segments``); every gather whose leading output dimension equals
    that size is counted against this budget.  ``boundary_sorted``
    additionally requires exactly one ``(dim, 2)``-shaped gather marked
    ``indices_are_sorted`` + ``unique_indices`` — the streaming
    two-lane boundary read of the single-pass bridge (PERF.md §8).
    """

    dim: str
    max_total: int
    max_random: int
    boundary_sorted: bool = False


@dataclass(frozen=True)
class KernelBudget:
    """The per-backend invariant contract checked by pass 1.

    Counting conventions: gathers/scatters inside a ``pallas_call``
    body are excluded (interpret-mode bodies re-express the Mosaic
    kernel as XLA ops; on the real chip they are not XLA gathers), and
    a "random" gather is one not marked ``indices_are_sorted``.
    """

    backend: str
    #: Max gathers without ``indices_are_sorted`` per step (all sizes).
    max_random_gathers: int
    #: Max scatter-family ops per step (``scatter``/``scatter-add``/...).
    max_scatters: int = 0
    #: f64 avals permitted anywhere in the step jaxpr.
    allow_f64: bool = False
    #: Exact number of ``psum``/``psum2`` collectives per step; any
    #: psum present must sit under a ``shard_map``.
    psum_count: int = 0
    #: Primitives that must appear somewhere in the step (e.g.
    #: ``dot_general`` for the MXU path, ``pallas_call`` for windowed).
    require_primitives: tuple[str, ...] = ()
    #: Size-classed gather budgets (see :class:`GatherBudget`).
    gather_budgets: tuple[GatherBudget, ...] = ()
    #: Converge-function arguments declared donated; the analyzer
    #: verifies the aliasing materialized in the lowered computation.
    donated_args: tuple[str, ...] = ()
    #: Free-form rationale recorded in ANALYSIS.json.
    notes: str = ""

    @property
    def invariant_count(self) -> int:
        """How many distinct invariants checking this budget evaluates
        (the acceptance floor is >= 3 per registered backend)."""
        n = 4  # random-gather, scatter, f64, callback checks always run
        n += 1  # psum count/placement is always asserted (incl. == 0)
        n += len(self.require_primitives)
        for gb in self.gather_budgets:
            n += 2 + (1 if gb.boundary_sorted else 0)
        if self.donated_args:
            n += 1
        return n


#: Backends that never enter jax — exempt from jaxpr analysis.
NON_JAX_BACKENDS = frozenset({"native-cpu"})

#: The table: backend name -> declared budget.  Populated by kernel
#: modules at import; read by ``protocol_tpu.analysis.invariants`` and
#: cross-checked against the ``trust/backend.py`` registry.
KERNEL_INVARIANTS: dict[str, KernelBudget] = {}


def declare(budget: KernelBudget) -> KernelBudget:
    """Register a kernel budget (idempotent per backend name; kernel
    modules call this at import time, next to the kernel they pin)."""
    KERNEL_INVARIANTS[budget.backend] = budget
    return budget


__all__ = [
    "GatherBudget",
    "KernelBudget",
    "KERNEL_INVARIANTS",
    "NON_JAX_BACKENDS",
    "declare",
]
