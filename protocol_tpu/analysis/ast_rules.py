"""Pass 2 — the AST repo-lint: hazards mypy/ruff don't model.

Rules over ``protocol_tpu/``, each an implicit-host-sync or
import-cost hazard the jaxpr pass can't see (it only traces registered
backends):

- ``host-op-in-jit`` (error): ``np.asarray``/``np.array``, ``.item()``,
  or ``float()``/``int()`` on a non-literal applied inside a
  ``@jax.jit``-decorated function.  On a traced value these force a
  host round-trip per call (or a tracer error at a distance); static
  shape math belongs outside the jit boundary.
- ``import-time-jnp`` (error, hot trees only): ``jnp.*`` array
  construction at module scope in ``ops/``, ``trust/``, ``parallel/``,
  ``node/``, ``obs/`` — it initializes the device backend (and
  possibly a TPU runtime grab) as an import side effect.
- ``bare-sync`` (error): a bare ``jax.device_get(...)`` or
  ``x.block_until_ready()`` expression statement whose result is
  discarded — a synchronization point that belongs in ``bench/`` or
  ``tests/``, not in library code.

Pass 3 — the observability-boundary rules (the obs subsystem's
"spans only at host boundaries" doctrine, enforced structurally):

- ``host-clock-in-jit`` (error): ``time.time()``/``perf_counter()``/
  ``monotonic()`` (or an obs span) inside a traced function — a
  ``@jit``- or ``shard_map``-decorated function, or any function
  nested in one.  A clock read there executes once at trace time and
  then lies forever, or (under a callback) syncs every iteration;
  per-iteration timing data belongs in the device-side loop carry
  (``ops.sparse.run_power_iteration``'s residual history), host
  timing at the jit boundary.
- ``logging-in-jit`` (error): ``logging``/``logger.*``/``log.*`` or
  ``print`` calls inside a traced function — same trace-time lie, and
  a ``jax.debug.print``-shaped rewrite would smuggle a callback into
  the hot loop.
- ``clock-in-kernel-tree`` (error): any use of the ``time`` or
  ``logging`` modules (or ``print``) anywhere in the device-kernel
  trees ``ops/`` and ``parallel/`` — instrumentation wraps kernels
  from the outside (``trust/backend.py``, ``node/``); the kernel
  modules themselves stay clock- and logger-free so no refactor can
  quietly move a host boundary inside one.

Pass 4 — the epoch-pipeline boundary rule (ISSUE 5):

- ``plan-mutation-in-converge`` (error): a ``WindowPlan`` mutation
  entry point (``apply_delta``/``replace_rows``) called inside a
  traced function.  Delta application is host-side layout surgery
  (numpy repacks, counting sorts) and must run strictly pre-dispatch
  — in ``Manager.prepare_epoch`` or the backend's plan-resolution
  step — never from the device-facing converge path, where it would
  trace host arrays into the kernel (or silently run once at trace
  time and serve a stale layout forever after).

Pass 5 — the flight-recorder boundary rule (ISSUE 6):

- ``journal-write-in-jit`` (error): a flight-recorder write
  (``JOURNAL.record``/``dump``/``flush`` or any
  ``record``/``dump``/``flush`` on a journal-named receiver) inside a
  jit- or shard_map-traced function.  Under a trace the event is
  recorded once at trace time and never again — the journal would
  "replay" a single stale event forever — and a callback-shaped
  rewrite would smuggle a host sync into the hot loop.  Journal
  writes happen at host boundaries (epoch tick, ingest, pipeline),
  exactly like spans and metrics.

Pass 6 — the admission-plane boundary rule (ISSUE 7):

- ``blocking-ingest-in-epoch-loop`` (error): synchronous signature
  verification (``verify_sig``/``eddsa_verify_batch``/
  ``verify_batch`` or the Manager ingest entry points
  ``add_attestation``/``add_attestations_bulk``), or a potentially
  unbounded blocking queue ``put()`` (no ``block=False``, no
  ``timeout=``), inside the epoch-loop code paths
  (``node/epoch.py`` / ``node/pipeline.py``).  Admission work
  belongs in the ingest plane (``protocol_tpu/ingest/``) behind its
  bounded queues; a signature check or an unbounded enqueue on the
  epoch path would re-couple the convergence cadence to ingest load
  — exactly the contention the admission tier exists to remove.

Pass 10 — the queue-observability rule (ISSUE 11):

- ``unobserved-queue`` (error): a bounded ``queue.Queue(maxsize=...)``
  constructed in a file with no queue-depth gauge write (a
  ``*QUEUE_DEPTH*.set(...)`` call, or a gauge registration whose
  metric name contains ``queue_depth``).  Every bounded queue is a
  backpressure point: when it fills, something sheds, coalesces, or
  blocks — and if its depth is not a first-class gauge, "the tier is
  saturated" degrades from a scrape to a guess.  The rule is
  file-scoped (the depth write lives next to the queue it observes);
  rings (``deque(maxlen=...)``) are excluded — they overwrite, never
  exert backpressure.

Pass 11 — the durability rules (ISSUE 14):

- ``non-atomic-state-write`` (error): a state-file write in ``node/``
  outside the sanctioned shapes — ``open()``/``os.fdopen()`` with a
  write/append mode, ``.write_text()``, or ``.write_bytes()`` in a
  function that is neither the checkpoint store's ``_atomic_write``
  helper (tmp + fsync + rename) nor fsync-disciplined (no ``fsync``
  call in the same function, the WAL's append path shape).  A bare
  ``open(path, "w")`` can be torn by a crash mid-write and the next
  boot reads garbage; durable node state goes through the atomic
  helper or carries its own fsync.
- ``fault-point-in-jit`` (error): a chaos hook (``chaos.fire`` /
  ``chaos.corrupt`` / ``chaos.wrap_file`` or any chaos-named
  receiver) inside a jit- or shard_map-traced function.  Under a
  trace the hook fires once at trace time and never again — the
  schedule silently stops covering that point — and a callback-shaped
  rewrite would smuggle a host sync into the kernel.  Fault points
  live at host boundaries, the same doctrine as spans (pass 3) and
  journal writes (pass 5).

Pass 12 — the memory-wall rules (ISSUE 15; evaluated by the memory
pass, ``python -m protocol_tpu.analysis --pass memory``, against the
long-lived node trees, with findings routed through the enumerated
``analysis/memory/waivers.py`` table):

- ``host-materialization-of-edges`` (error): ``np.asarray`` /
  ``np.array`` / ``jax.device_get`` on an edge-scale array (or an
  edge-scale ``.tolist()``) on the epoch loop's critical path
  (``node/epoch.py`` / ``node/pipeline.py``).  An edge table is
  hundreds of MB at the 50M-edge shape; materializing one on the host
  per tick doubles the footprint and serializes a device->host copy
  into the epoch cadence.  Edge-scale host work is plan build
  (``Manager.prepare_epoch``), never the loop.
- ``unbounded-cache-growth`` (error): a cache-named dict/list
  attribute (``*cache*``) of a long-lived class in ``node/`` or
  ``ingest/`` that grows (subscript store / ``append`` / ``add`` /
  ``update`` / ``setdefault``) with no eviction anywhere in the class
  — no ``pop``/``popitem``/``clear``, no ``del``, no generation
  rotation (reassignment outside ``__init__``).  The ingest dedup
  cache's two-generation rotation and the pipeline's outcome ring set
  the precedent for what "bounded" looks like; an epoch-keyed cache
  without eviction leaks with uptime (a cached f32[N] score vector
  per epoch is 4 MB/epoch at 1M peers — 34 GB/day at a 10 s cadence).

Pass 9 — the proving-plane boundary rule (ISSUE 10):

- ``blocking-prove-in-epoch-loop`` (error): a synchronous prover
  entry point (``plonk.prove`` / ``prover.prove`` /
  ``calculate_proofs`` / ``prove_epoch_statement`` /
  ``aggregate_proofs`` or the aggregator's ``accumulate``) inside
  the epoch-loop code paths (``node/epoch.py`` /
  ``node/pipeline.py``).  A SNARK is seconds of whole-core native
  work; on the epoch path it re-serializes proving into the epoch
  cadence — the exact coupling the async proving plane
  (``protocol_tpu/prover/``) exists to remove.  Epoch-loop code
  enqueues a :class:`~protocol_tpu.prover.jobs.ProofJob` and moves
  on; proving belongs in the plane's worker pool.
"""

from __future__ import annotations

import ast
from pathlib import Path

from .report import Finding

#: Trees where import-time device work is a hard error (the modules the
#: node imports on its boot path).
HOT_TREES = ("ops", "trust", "parallel", "node", "obs")

#: Device-kernel trees: no clock, no logging, no print anywhere — the
#: obs instrumentation layer wraps these modules from the outside
#: (trust/backend.py, node/), never from within.
KERNEL_TREES = ("ops", "parallel")

#: The epoch loop's critical path: no synchronous signature
#: verification, no unbounded blocking queue puts (pass 6) — ingest
#: work stays in the admission plane behind its bounded queues.
EPOCH_LOOP_FILES = (
    "protocol_tpu/node/epoch.py",
    "protocol_tpu/node/pipeline.py",
)

#: jnp attributes that are plain dtypes/constants, not array factories.
_JNP_DTYPE_NAMES = frozenset(
    {
        "bfloat16",
        "bool_",
        "complex64",
        "complex128",
        "dtype",
        "finfo",
        "float16",
        "float32",
        "float64",
        "iinfo",
        "inf",
        "int8",
        "int16",
        "int32",
        "int64",
        "nan",
        "newaxis",
        "pi",
        "uint8",
        "uint16",
        "uint32",
        "uint64",
    }
)

_NUMPY_ALIASES = frozenset({"np", "numpy"})
_JNP_ALIASES = frozenset({"jnp"})


def _dotted(node: ast.expr) -> str | None:
    """``a.b.c`` -> "a.b.c" for Name/Attribute chains, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


#: Decorator names that make a function body traced code: its Python
#: executes once at trace time, so host side effects inside lie.
_JIT_NAMES = ("jit", "jax.jit")
_SHARD_MAP_NAMES = (
    "shard_map",
    "_shard_map",
    "jax.shard_map",
    "jax.experimental.shard_map.shard_map",
)


def _is_traced_decorator(dec: ast.expr, names: tuple[str, ...]) -> bool:
    """True for ``@f``, ``@mod.f``, ``@partial(f, ...)``,
    ``@functools.partial(f, ...)``, and ``@f(...)`` for any ``f`` in
    ``names``."""
    if isinstance(dec, ast.Call):
        name = _dotted(dec.func)
        if name in ("partial", "functools.partial") and dec.args:
            return _dotted(dec.args[0]) in names
        return name in names
    return _dotted(dec) in names


def _is_jit_decorator(dec: ast.expr) -> bool:
    return _is_traced_decorator(dec, _JIT_NAMES)


def _is_literal(node: ast.expr) -> bool:
    return isinstance(node, ast.Constant) or (
        isinstance(node, ast.UnaryOp) and isinstance(node.operand, ast.Constant)
    )


#: Host clock reads (module-qualified and ``from time import ...`` bare
#: forms) — pass-3 hazards inside traced functions and kernel trees.
_CLOCK_CALLS = frozenset(
    {
        "time.time",
        "time.time_ns",
        "time.perf_counter",
        "time.perf_counter_ns",
        "time.monotonic",
        "time.monotonic_ns",
        "time.process_time",
        "perf_counter",
        "perf_counter_ns",
        "monotonic",
        "monotonic_ns",
    }
)
_LOGGING_METHODS = frozenset(
    {"debug", "info", "warning", "warn", "error", "exception", "critical", "log"}
)


def _is_clock_call(name: str | None) -> bool:
    return name is not None and name in _CLOCK_CALLS


def _is_logging_call(name: str | None) -> bool:
    """``logging.*``, ``log.<level>``/``logger.<level>``/``self.log.*``
    receivers, and bare ``print``."""
    if name is None:
        return False
    if name == "print":
        return True
    root, _, rest = name.partition(".")
    if root == "logging":
        return True
    leaf = name.rsplit(".", 1)[-1]
    receiver = name.rsplit(".", 2)[-2] if "." in name else ""
    return leaf in _LOGGING_METHODS and receiver in ("log", "logger")


#: WindowPlan mutation entry points — host-side layout surgery that
#: must never run under a trace (pass 4).
_PLAN_MUTATION_METHODS = frozenset({"apply_delta", "replace_rows"})


def _is_plan_mutation_call(name: str | None) -> bool:
    """``<anything>.apply_delta(...)`` / ``<anything>.replace_rows(...)``
    — the delta surface is small and uniquely named, so matching the
    method leaf is precise enough for a lint."""
    return name is not None and name.rsplit(".", 1)[-1] in _PLAN_MUTATION_METHODS


#: Flight-recorder write entry points (pass 5).
_JOURNAL_METHODS = frozenset({"record", "dump", "flush"})


def _is_journal_call(name: str | None) -> bool:
    """``JOURNAL.<write>(...)`` or ``<journalish>.<write>(...)`` where
    the receiver names a journal/flight recorder — matching the method
    leaf alone would catch unrelated ``.record()`` APIs, so the
    receiver must look like the recorder."""
    if name is None or "." not in name:
        return False
    receiver, leaf = name.rsplit(".", 1)
    if leaf not in _JOURNAL_METHODS:
        return False
    tail = receiver.rsplit(".", 1)[-1].lower()
    return "journal" in tail or "flight" in tail or tail == "recorder"


#: Synchronous signature-verification entry points (pass 6): the
#: crypto verifiers and the Manager ingest methods that call them.
_SYNC_VERIFY_LEAVES = frozenset(
    {
        "verify_sig",
        "eddsa_verify_batch",
        "verify_batch",
        "add_attestation",
        "add_attestations_bulk",
    }
)


def _is_sync_verify_call(name: str | None) -> bool:
    return name is not None and name.rsplit(".", 1)[-1] in _SYNC_VERIFY_LEAVES


#: Synchronous proving entry points (pass 9): the PLONK/commitment
#: prove surface, the statement synthesizer, and the aggregator —
#: seconds of whole-core native work that must never run on the epoch
#: loop's critical path (the proving plane's job queue is the only
#: sanctioned hand-off).  ``submit``/``prove_job`` via the plane pass.
_SYNC_PROVE_LEAVES = frozenset(
    {
        "prove",
        "calculate_proofs",
        "prove_epoch_statement",
        "aggregate_proofs",
        "accumulate",
    }
)


def _is_sync_prove_call(name: str | None) -> bool:
    return name is not None and name.rsplit(".", 1)[-1] in _SYNC_PROVE_LEAVES


def _is_unbounded_put(node: ast.Call, name: str | None) -> bool:
    """``<q>.put(item)`` with neither ``block=False`` nor a
    ``timeout=`` — a potentially unbounded block.  ``put_nowait`` and
    explicitly-bounded puts pass."""
    if name is None or name.rsplit(".", 1)[-1] != "put":
        return False
    if not isinstance(node.func, ast.Attribute):
        return False
    if len(node.args) >= 2:  # explicit positional block arg
        return False
    for kw in node.keywords:
        if kw.arg in ("block", "timeout"):
            return False
    return True


#: Bounded-queue constructors the unobserved-queue rule tracks
#: (pass 10).  Rings (deque(maxlen=...)) are excluded by design: they
#: overwrite instead of backing pressure up, so depth is not a
#: saturation signal there.
_QUEUE_CONSTRUCTORS = frozenset({"queue.Queue", "Queue", "queue.PriorityQueue", "queue.LifoQueue"})


def _is_bounded_queue_ctor(node: ast.Call, name: str | None) -> bool:
    """``queue.Queue(maxsize=N)`` (or positional) with a bound that is
    not literally 0/None — an unbounded queue has no depth-saturation
    semantics to observe."""
    if name not in _QUEUE_CONSTRUCTORS:
        return False
    bound: ast.expr | None = None
    if node.args:
        bound = node.args[0]
    for kw in node.keywords:
        if kw.arg == "maxsize":
            bound = kw.value
    if bound is None:
        return False
    if isinstance(bound, ast.Constant) and not bound.value:
        return False  # maxsize=0/None = unbounded
    if (
        isinstance(bound, ast.UnaryOp)
        and isinstance(bound.operand, ast.Constant)
    ):
        return False  # maxsize=-1 = unbounded
    return True


def _is_depth_gauge_write(node: ast.Call, name: str | None) -> bool:
    """A queue-depth observation: ``<...QUEUE_DEPTH...>.set(...)`` on
    the metric registry, or a ``.gauge("...queue_depth...")``
    registration."""
    if name is None:
        return False
    receiver, _, leaf = name.rpartition(".")
    if leaf == "set" and "queue_depth" in receiver.lower():
        return True
    if leaf == "gauge" and node.args:
        first = node.args[0]
        return (
            isinstance(first, ast.Constant)
            and isinstance(first.value, str)
            and "queue_depth" in first.value.lower()
        )
    return False


#: Pass-12 host-materialization entry points: calls that force a full
#: device->host copy of their operand.
_MATERIALIZE_CALLS = frozenset(
    {"np.asarray", "np.array", "numpy.asarray", "numpy.array", "jax.device_get"}
)

#: Identifier tokens that mark an array as edge-scale (the O(E) data:
#: edge endpoints/weights, window-plan rows/slots, segment tables).
_EDGE_NAME_TOKENS = frozenset(
    {"src", "dst", "edge", "edges", "weight", "weights", "wid", "seg",
     "segs", "local"}
)


def _is_edge_name(name: str | None) -> bool:
    """True when a dotted name's leaf looks like an edge-scale array
    (``plan.seg_dst``, ``graph.src``, ``self._edge_weights``)."""
    if name is None:
        return False
    leaf = name.rsplit(".", 1)[-1].lower()
    return any(tok in _EDGE_NAME_TOKENS for tok in leaf.split("_") if tok)


def _materialized_edge_name(node: ast.Call, name: str | None) -> str | None:
    """The edge-scale dotted name a pass-12 materialization call moves
    to the host, or None: ``np.asarray(<edge>)`` / ``jax.device_get(
    <edge>)`` by first argument, ``<edge>.tolist()`` by receiver."""
    if name is None:
        return None
    if name in _MATERIALIZE_CALLS and node.args:
        arg = _dotted(node.args[0])
        if _is_edge_name(arg):
            return f"{name}({arg})"
        return None
    if name.rsplit(".", 1)[-1] == "tolist" and "." in name:
        receiver = name.rsplit(".", 1)[0]
        if _is_edge_name(receiver):
            return f"{receiver}.tolist()"
    return None


#: Pass-12 cache-growth bookkeeping: cache-named attributes, the calls
#: that grow them, and the calls that count as eviction.
_CACHE_GROW_LEAVES = frozenset({"append", "add", "update", "setdefault"})
_CACHE_EVICT_LEAVES = frozenset({"pop", "popitem", "clear"})


def _is_cache_attr_name(attr: str) -> bool:
    return "cache" in attr.lower()


def _empty_container_ctor(value: ast.expr) -> bool:
    """``{}`` / ``[]`` / ``dict(...)`` / ``list()`` / ``defaultdict(...)``
    — the shapes a growable cache starts from."""
    if isinstance(value, (ast.Dict, ast.List)):
        return True
    if isinstance(value, ast.Call):
        ctor = _dotted(value.func)
        return ctor is not None and ctor.rsplit(".", 1)[-1] in (
            "dict", "list", "defaultdict", "OrderedDict",
        )
    return False


def _self_attr(node: ast.expr) -> str | None:
    """``self.<attr>`` -> attr name, else None."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


#: Chaos hook entry points (pass 11): host-boundary-only, like spans.
_CHAOS_LEAVES = frozenset({"fire", "corrupt", "wrap_file"})


def _is_chaos_call(name: str | None) -> bool:
    """``chaos.fire(...)`` / ``CHAOS.corrupt(...)`` / any
    chaos-named receiver calling a hook leaf."""
    if name is None or "." not in name:
        return False
    receiver, leaf = name.rsplit(".", 1)
    if leaf not in _CHAOS_LEAVES:
        return False
    return "chaos" in receiver.rsplit(".", 1)[-1].lower()


#: File-write entry points the non-atomic-state-write rule tracks
#: (pass 11).  ``.write()`` on an already-open handle is exempt — the
#: open is the decision point.
_WRITE_OPENERS = frozenset({"open", "os.fdopen", "io.open", "gzip.open"})
_WRITE_METHOD_LEAVES = frozenset({"write_text", "write_bytes"})
_WRITE_MODES = frozenset("wax+")


def _is_state_write_call(node: ast.Call, name: str | None) -> bool:
    """An ``open()``-family call with a write/append/create mode, or a
    pathlib ``.write_text()``/``.write_bytes()``."""
    if name is None:
        return False
    if name.rsplit(".", 1)[-1] in _WRITE_METHOD_LEAVES and isinstance(
        node.func, ast.Attribute
    ):
        return True
    if name not in _WRITE_OPENERS:
        return False
    mode: ast.expr | None = node.args[1] if len(node.args) >= 2 else None
    for kw in node.keywords:
        if kw.arg == "mode":
            mode = kw.value
    return (
        isinstance(mode, ast.Constant)
        and isinstance(mode.value, str)
        and bool(set(mode.value) & _WRITE_MODES)
    )


def _is_fsync_call(name: str | None) -> bool:
    return name is not None and name.rsplit(".", 1)[-1] == "fsync"


def _is_span_call(name: str | None) -> bool:
    """obs span entry points (``TRACER.span``/``TRACER.epoch`` or any
    ``*.span(...)``) — host boundaries by definition, so inside a
    traced function they are always a bug."""
    if name is None:
        return False
    return name.endswith(".span") or name in ("TRACER.epoch", "TRACER.span")


class _Visitor(ast.NodeVisitor):
    def __init__(
        self,
        rel_path: str,
        hot: bool,
        kernel_tree: bool = False,
        epoch_loop: bool = False,
        node_tree: bool = False,
        mem_rules: bool = False,
    ) -> None:
        self.rel_path = rel_path
        self.hot = hot
        self.kernel_tree = kernel_tree
        self.epoch_loop = epoch_loop
        self.node_tree = node_tree
        #: Pass-12 rules armed (the memory pass scans the long-lived
        #: trees with these on; the plain AST pass leaves them off so
        #: findings are never double-reported across passes).
        self.mem_rules = mem_rules
        #: Pass-12 per-class state: cache-named container attrs
        #: initialized in __init__ vs growth/eviction evidence,
        #: resolved when the ClassDef closes.
        self._class_frames: list[dict] = []
        #: Pass-11 per-function state: write sites collected until the
        #: function closes, when the _atomic_write/fsync exemptions
        #: resolve (the discipline lives in the same function as the
        #: open, by doctrine).
        self._fn_frames: list[dict] = []
        self.jit_depth = 0
        #: Depth inside jit- OR shard_map-decorated functions (pass 3):
        #: shard_map bodies are traced exactly like jit bodies.
        self.traced_depth = 0
        self.fn_depth = 0
        self.findings: list[Finding] = []
        #: Pass-10 file-level state: bounded-queue construction sites
        #: vs whether any queue-depth gauge write exists in this file
        #: (resolved after the walk, in scan_source).
        self.bounded_queue_sites: list[ast.AST] = []
        self.has_depth_gauge = False

    def _emit(self, rule: str, message: str, node: ast.AST) -> None:
        self.findings.append(
            Finding(
                pass_name="ast",
                rule=rule,
                severity="error",
                message=message,
                file=self.rel_path,
                line=getattr(node, "lineno", None),
            )
        )

    # -- function scope tracking ---------------------------------------

    def _visit_function(self, node: ast.FunctionDef | ast.AsyncFunctionDef) -> None:
        jitted = any(_is_jit_decorator(d) for d in node.decorator_list)
        traced = jitted or any(
            _is_traced_decorator(d, _SHARD_MAP_NAMES) for d in node.decorator_list
        )
        self.fn_depth += 1
        self.jit_depth += 1 if jitted else 0
        self.traced_depth += 1 if traced else 0
        self._fn_frames.append({"name": node.name, "writes": [], "fsync": False})
        self.generic_visit(node)
        frame = self._fn_frames.pop()
        if (
            frame["writes"]
            and not frame["name"].startswith("_atomic_write")
            and not frame["fsync"]
        ):
            for site in frame["writes"]:
                self._emit_state_write(site)
        self.traced_depth -= 1 if traced else 0
        self.jit_depth -= 1 if jitted else 0
        self.fn_depth -= 1

    def _emit_state_write(self, site: ast.AST) -> None:
        self._emit(
            "non-atomic-state-write",
            "state-file write in node/ outside the _atomic_write helper "
            "and without fsync discipline in the same function — a crash "
            "mid-write tears the file and the next boot reads garbage; "
            "route durable state through CheckpointStore._atomic_write "
            "(tmp + fsync + rename) or fsync what you append "
            "(node/wal.py)",
            site,
        )

    visit_FunctionDef = _visit_function
    visit_AsyncFunctionDef = _visit_function

    # -- pass 12: unbounded cache growth (class-scoped) ------------------

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        if not self.mem_rules:
            self.generic_visit(node)
            return
        self._class_frames.append(
            {"inits": {}, "grows": set(), "evicts": set()}
        )
        self.generic_visit(node)
        frame = self._class_frames.pop()
        for attr, site in frame["inits"].items():
            if attr in frame["grows"] and attr not in frame["evicts"]:
                self._emit(
                    "unbounded-cache-growth",
                    f"cache attribute {node.name}.{attr} of a long-lived "
                    f"class grows with no eviction, size bound, or "
                    f"epoch rotation anywhere in the class — an "
                    f"epoch-keyed cache without eviction leaks with "
                    f"uptime (the ingest dedup cache's generation "
                    f"rotation and the pipeline's outcome ring are the "
                    f"sanctioned shapes)",
                    site,
                )

    def _in_init(self) -> bool:
        return bool(self._fn_frames) and self._fn_frames[-1]["name"] == "__init__"

    def _note_cache_assign(self, target: ast.expr, value: ast.expr | None,
                           node: ast.stmt) -> None:
        """Pass-12 bookkeeping for one assignment statement."""
        if not self._class_frames:
            return
        frame = self._class_frames[-1]
        if isinstance(target, ast.Subscript):
            attr = _self_attr(target.value)
            if attr is not None and _is_cache_attr_name(attr):
                frame["grows"].add(attr)
            return
        attr = _self_attr(target)
        if attr is None or not _is_cache_attr_name(attr):
            return
        if self._in_init():
            if value is not None and _empty_container_ctor(value):
                frame["inits"][attr] = node
        else:
            # Reassignment outside __init__ is generation rotation —
            # the dedup-cache shape — and counts as eviction.
            frame["evicts"].add(attr)

    def visit_Assign(self, node: ast.Assign) -> None:
        if self.mem_rules:
            for target in node.targets:
                self._note_cache_assign(target, node.value, node)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if self.mem_rules:
            self._note_cache_assign(node.target, node.value, node)
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete) -> None:
        if self.mem_rules and self._class_frames:
            for target in node.targets:
                if isinstance(target, ast.Subscript):
                    attr = _self_attr(target.value)
                    if attr is not None and _is_cache_attr_name(attr):
                        self._class_frames[-1]["evicts"].add(attr)
        self.generic_visit(node)

    # -- rules ----------------------------------------------------------

    def visit_Call(self, node: ast.Call) -> None:
        name = _dotted(node.func)
        # Pass 10 bookkeeping: bounded-queue constructions vs depth-
        # gauge writes, resolved per-file after the walk.
        if _is_bounded_queue_ctor(node, name):
            self.bounded_queue_sites.append(node)
        elif _is_depth_gauge_write(node, name):
            self.has_depth_gauge = True
        if self.mem_rules and self._class_frames and isinstance(
            node.func, ast.Attribute
        ):
            # Pass-12 bookkeeping: self.<cache>.append/add/update grows,
            # self.<cache>.pop/popitem/clear evicts.
            attr = _self_attr(node.func.value)
            if attr is not None and _is_cache_attr_name(attr):
                if node.func.attr in _CACHE_GROW_LEAVES:
                    self._class_frames[-1]["grows"].add(attr)
                elif node.func.attr in _CACHE_EVICT_LEAVES:
                    self._class_frames[-1]["evicts"].add(attr)
        if self.mem_rules and self.epoch_loop:
            # Pass 12: no edge-scale host materialization on the epoch
            # loop's critical path — an edge table is hundreds of MB at
            # the 50M-edge shape, and the copy serializes into the tick.
            offender = _materialized_edge_name(node, name)
            if offender is not None:
                self._emit(
                    "host-materialization-of-edges",
                    f"{offender} on an epoch-loop code path materializes "
                    "an edge-scale array on the host: O(E) bytes copied "
                    "device->host per tick, doubling the footprint the "
                    "memory budgets pin — edge-scale host work belongs "
                    "in plan build (Manager.prepare_epoch), never the "
                    "loop",
                    node,
                )
        if self.node_tree:
            # Pass 11 bookkeeping: write sites vs the enclosing
            # function's fsync discipline (resolved at function close;
            # module-scope writes have no exemption to wait for).
            if _is_fsync_call(name) and self._fn_frames:
                self._fn_frames[-1]["fsync"] = True
            elif _is_state_write_call(node, name):
                if self._fn_frames:
                    self._fn_frames[-1]["writes"].append(node)
                else:
                    self._emit_state_write(node)
        if self.jit_depth > 0:
            if name is not None:
                root = name.split(".", 1)[0]
                if root in _NUMPY_ALIASES:
                    self._emit(
                        "host-op-in-jit",
                        f"{name}() inside a @jit function materializes "
                        "traced values on the host",
                        node,
                    )
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "item"
                and not node.args
            ):
                self._emit(
                    "host-op-in-jit",
                    ".item() inside a @jit function forces a host sync",
                    node,
                )
            if (
                name in ("float", "int")
                and node.args
                and not _is_literal(node.args[0])
            ):
                self._emit(
                    "host-op-in-jit",
                    f"{name}() on a non-literal inside a @jit function "
                    "concretizes a traced value",
                    node,
                )
        if self.traced_depth > 0:
            # Pass 3: the obs boundary doctrine — no clocks, spans, or
            # logging inside traced code (jit or shard_map bodies).
            if _is_clock_call(name) or _is_span_call(name):
                self._emit(
                    "host-clock-in-jit",
                    f"{name}() inside a traced function reads the host "
                    "clock at trace time (spans/timing belong at the "
                    "jit boundary; per-iteration data in the loop carry)",
                    node,
                )
            elif _is_logging_call(name):
                self._emit(
                    "logging-in-jit",
                    f"{name}() inside a traced function executes once "
                    "at trace time, not per call — log at the host "
                    "boundary instead",
                    node,
                )
            elif _is_journal_call(name):
                self._emit(
                    "journal-write-in-jit",
                    f"{name}() inside a traced function records once at "
                    "trace time and never again — flight-recorder writes "
                    "belong at host boundaries (epoch tick, ingest, "
                    "pipeline), never in traced code",
                    node,
                )
            elif _is_chaos_call(name):
                self._emit(
                    "fault-point-in-jit",
                    f"{name}() inside a traced function fires once at "
                    "trace time and never again — the chaos schedule "
                    "silently stops covering this point, and a callback "
                    "rewrite would smuggle a host sync into the kernel; "
                    "fault points live at host boundaries (pass 3/5 "
                    "doctrine)",
                    node,
                )
            elif _is_plan_mutation_call(name):
                self._emit(
                    "plan-mutation-in-converge",
                    f"{name}() inside a traced function: WindowPlan "
                    "delta application is host-side layout surgery and "
                    "must run pre-dispatch (Manager.prepare_epoch / the "
                    "backend's plan resolution), never from the "
                    "device-facing converge path",
                    node,
                )
        elif self.kernel_tree and (
            _is_clock_call(name) or _is_logging_call(name)
        ):
            self._emit(
                "clock-in-kernel-tree",
                f"{name}() in a device-kernel tree ({'/'.join(KERNEL_TREES)}): "
                "instrumentation wraps kernels from trust/ and node/, "
                "never from inside ops/ or parallel/",
                node,
            )
        if self.epoch_loop:
            # Pass 6: the epoch loop must never verify signatures or
            # block on an unbounded enqueue — admission work lives in
            # the ingest plane behind bounded queues.
            if _is_sync_verify_call(name):
                self._emit(
                    "blocking-ingest-in-epoch-loop",
                    f"{name}() on an epoch-loop code path: signature "
                    "verification belongs in the admission plane "
                    "(protocol_tpu/ingest/), not in node/epoch.py or "
                    "node/pipeline.py where it re-couples convergence "
                    "cadence to ingest load",
                    node,
                )
            elif _is_unbounded_put(node, name):
                self._emit(
                    "blocking-ingest-in-epoch-loop",
                    f"{name}() without block=False or timeout= on an "
                    "epoch-loop code path: an unbounded blocking "
                    "enqueue can stall the epoch loop indefinitely — "
                    "use put_nowait (coalescing backpressure) or a "
                    "bounded timeout",
                    node,
                )
            elif _is_sync_prove_call(name):
                # Pass 9: the epoch loop never proves synchronously —
                # a SNARK is seconds of whole-core work; enqueue a
                # ProofJob on the proving plane instead.
                self._emit(
                    "blocking-prove-in-epoch-loop",
                    f"{name}() on an epoch-loop code path: synchronous "
                    "proving re-serializes the SNARK into the epoch "
                    "cadence — enqueue a ProofJob on the proving plane "
                    "(protocol_tpu/prover/) and let the worker pool "
                    "prove it off the critical path",
                    node,
                )
        if (
            self.fn_depth == 0
            and self.hot
            and name is not None
            and name.split(".", 1)[0] in _JNP_ALIASES
        ):
            attr = name.split(".", 1)[1] if "." in name else ""
            if attr not in _JNP_DTYPE_NAMES:
                self._emit(
                    "import-time-jnp",
                    f"{name}() at module import time in a hot module "
                    "initializes the device backend as an import side "
                    "effect",
                    node,
                )
        self.generic_visit(node)

    def _check_kernel_import(self, node: ast.stmt, module: str | None) -> None:
        if self.kernel_tree and module is not None and module.split(".")[0] in (
            "time",
            "logging",
        ):
            self._emit(
                "clock-in-kernel-tree",
                f"import of {module!r} in a device-kernel tree — clocks "
                "and loggers stay outside ops/ and parallel/ (spans at "
                "host boundaries only)",
                node,
            )

    def visit_Import(self, node: ast.Import) -> None:
        for alias in node.names:
            self._check_kernel_import(node, alias.name)
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        self._check_kernel_import(node, node.module)
        self.generic_visit(node)

    def visit_Expr(self, node: ast.Expr) -> None:
        if isinstance(node.value, ast.Call):
            name = _dotted(node.value.func)
            bare_sync = name == "jax.device_get" or (
                isinstance(node.value.func, ast.Attribute)
                and node.value.func.attr == "block_until_ready"
            )
            if bare_sync:
                self._emit(
                    "bare-sync",
                    "bare device sync (result discarded) outside bench/ "
                    "and tests/",
                    node,
                )
        self.generic_visit(node)


def _in_tree(rel_path: str, trees: tuple[str, ...]) -> bool:
    parts = Path(rel_path).parts
    return len(parts) >= 2 and parts[0] == "protocol_tpu" and parts[1] in trees


def _is_hot(rel_path: str) -> bool:
    return _in_tree(rel_path, HOT_TREES)


def scan_source(
    source: str, rel_path: str, mem_rules: bool = False
) -> list[Finding]:
    """Run the AST ruleset over in-memory source (seeded violation
    fixtures use this; ``scan_file`` is the on-disk wrapper).
    ``mem_rules`` arms the pass-12 rules — the memory pass's AST leg;
    the plain AST pass leaves them off so the two passes never
    double-report."""
    try:
        tree = ast.parse(source, filename=rel_path)
    except SyntaxError as exc:
        return [
            Finding(
                pass_name="ast",
                rule="syntax-error",
                severity="error",
                message=str(exc),
                file=rel_path,
                line=exc.lineno,
            )
        ]
    visitor = _Visitor(
        rel_path,
        hot=_is_hot(rel_path),
        kernel_tree=_in_tree(rel_path, KERNEL_TREES),
        epoch_loop=rel_path in EPOCH_LOOP_FILES,
        node_tree=_in_tree(rel_path, ("node",)),
        mem_rules=mem_rules,
    )
    visitor.visit(tree)
    if visitor.bounded_queue_sites and not visitor.has_depth_gauge:
        # Pass 10: every bounded queue is a backpressure point; its
        # depth must be a registered gauge in the same file, or
        # saturation is a guess instead of a scrape.
        for site in visitor.bounded_queue_sites:
            visitor._emit(
                "unobserved-queue",
                "bounded queue constructed with no queue-depth gauge "
                "write in this file — register a "
                "*_queue_depth gauge (obs/metrics.py) and .set() it "
                "where the queue's depth changes, so backpressure is "
                "scrapeable",
                site,
            )
    return visitor.findings


def scan_file(path: Path, root: Path) -> list[Finding]:
    rel = str(path.relative_to(root))
    return scan_source(path.read_text(), rel)


def run_ast_pass(root: str | Path | None = None) -> tuple[list[Finding], int]:
    """Scan ``protocol_tpu/`` under ``root`` (default: the repo this
    package was imported from).  Returns ``(findings, files_scanned)``."""
    if root is None:
        root = Path(__file__).resolve().parent.parent.parent
    root = Path(root)
    findings: list[Finding] = []
    files = sorted((root / "protocol_tpu").rglob("*.py"))
    for path in files:
        findings.extend(scan_file(path, root))
    return findings, len(files)


#: Rules the memory pass's AST leg reports (everything else the armed
#: visitor would emit is the plain AST pass's job — filtering here
#: keeps ``--pass all`` from reporting the same finding twice).
MEM_AST_RULES = frozenset(
    {"host-materialization-of-edges", "unbounded-cache-growth"}
)

#: Long-lived trees the pass-12 AST rules police: the node (epoch loop,
#: manager, checkpoint) and admission-plane classes live for the
#: process, so an unevicted cache there leaks with uptime.
MEM_AST_TREES = ("node", "ingest")


def run_mem_ast_pass(root: str | Path | None = None) -> tuple[list[Finding], int]:
    """Pass 12's AST leg: scan the long-lived trees with the memory
    rules armed; returns ``(mem-rule findings, files scanned)``."""
    if root is None:
        root = Path(__file__).resolve().parent.parent.parent
    root = Path(root)
    findings: list[Finding] = []
    files = [
        path
        for tree in MEM_AST_TREES
        for path in sorted((root / "protocol_tpu" / tree).rglob("*.py"))
    ]
    for path in files:
        rel = str(path.relative_to(root))
        found = scan_source(path.read_text(), rel, mem_rules=True)
        findings.extend(f for f in found if f.rule in MEM_AST_RULES)
    return findings, len(files)


__all__ = [
    "EPOCH_LOOP_FILES",
    "HOT_TREES",
    "KERNEL_TREES",
    "MEM_AST_RULES",
    "MEM_AST_TREES",
    "run_ast_pass",
    "run_mem_ast_pass",
    "scan_file",
    "scan_source",
]
