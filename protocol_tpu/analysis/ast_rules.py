"""Pass 2 — the AST repo-lint: hazards mypy/ruff don't model.

Three rules over ``protocol_tpu/``, each an implicit-host-sync or
import-cost hazard the jaxpr pass can't see (it only traces registered
backends):

- ``host-op-in-jit`` (error): ``np.asarray``/``np.array``, ``.item()``,
  or ``float()``/``int()`` on a non-literal applied inside a
  ``@jax.jit``-decorated function.  On a traced value these force a
  host round-trip per call (or a tracer error at a distance); static
  shape math belongs outside the jit boundary.
- ``import-time-jnp`` (error, hot trees only): ``jnp.*`` array
  construction at module scope in ``ops/``, ``trust/``, ``parallel/``,
  ``node/`` — it initializes the device backend (and possibly a TPU
  runtime grab) as an import side effect.
- ``bare-sync`` (error): a bare ``jax.device_get(...)`` or
  ``x.block_until_ready()`` expression statement whose result is
  discarded — a synchronization point that belongs in ``bench/`` or
  ``tests/``, not in library code.
"""

from __future__ import annotations

import ast
from pathlib import Path

from .report import Finding

#: Trees where import-time device work is a hard error (the modules the
#: node imports on its boot path).
HOT_TREES = ("ops", "trust", "parallel", "node")

#: jnp attributes that are plain dtypes/constants, not array factories.
_JNP_DTYPE_NAMES = frozenset(
    {
        "bfloat16",
        "bool_",
        "complex64",
        "complex128",
        "dtype",
        "finfo",
        "float16",
        "float32",
        "float64",
        "iinfo",
        "inf",
        "int8",
        "int16",
        "int32",
        "int64",
        "nan",
        "newaxis",
        "pi",
        "uint8",
        "uint16",
        "uint32",
        "uint64",
    }
)

_NUMPY_ALIASES = frozenset({"np", "numpy"})
_JNP_ALIASES = frozenset({"jnp"})


def _dotted(node: ast.expr) -> str | None:
    """``a.b.c`` -> "a.b.c" for Name/Attribute chains, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_jit_decorator(dec: ast.expr) -> bool:
    """True for ``@jit``, ``@jax.jit``, ``@partial(jax.jit, ...)``,
    ``@functools.partial(jax.jit, ...)``, and ``@jax.jit(...)``."""
    if isinstance(dec, ast.Call):
        name = _dotted(dec.func)
        if name in ("partial", "functools.partial") and dec.args:
            return _dotted(dec.args[0]) in ("jit", "jax.jit")
        return name in ("jit", "jax.jit")
    return _dotted(dec) in ("jit", "jax.jit")


def _is_literal(node: ast.expr) -> bool:
    return isinstance(node, ast.Constant) or (
        isinstance(node, ast.UnaryOp) and isinstance(node.operand, ast.Constant)
    )


class _Visitor(ast.NodeVisitor):
    def __init__(self, rel_path: str, hot: bool) -> None:
        self.rel_path = rel_path
        self.hot = hot
        self.jit_depth = 0
        self.fn_depth = 0
        self.findings: list[Finding] = []

    def _emit(self, rule: str, message: str, node: ast.AST) -> None:
        self.findings.append(
            Finding(
                pass_name="ast",
                rule=rule,
                severity="error",
                message=message,
                file=self.rel_path,
                line=getattr(node, "lineno", None),
            )
        )

    # -- function scope tracking ---------------------------------------

    def _visit_function(self, node: ast.FunctionDef | ast.AsyncFunctionDef) -> None:
        jitted = any(_is_jit_decorator(d) for d in node.decorator_list)
        self.fn_depth += 1
        self.jit_depth += 1 if jitted else 0
        self.generic_visit(node)
        self.jit_depth -= 1 if jitted else 0
        self.fn_depth -= 1

    visit_FunctionDef = _visit_function
    visit_AsyncFunctionDef = _visit_function

    # -- rules ----------------------------------------------------------

    def visit_Call(self, node: ast.Call) -> None:
        name = _dotted(node.func)
        if self.jit_depth > 0:
            if name is not None:
                root = name.split(".", 1)[0]
                if root in _NUMPY_ALIASES:
                    self._emit(
                        "host-op-in-jit",
                        f"{name}() inside a @jit function materializes "
                        "traced values on the host",
                        node,
                    )
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "item"
                and not node.args
            ):
                self._emit(
                    "host-op-in-jit",
                    ".item() inside a @jit function forces a host sync",
                    node,
                )
            if (
                name in ("float", "int")
                and node.args
                and not _is_literal(node.args[0])
            ):
                self._emit(
                    "host-op-in-jit",
                    f"{name}() on a non-literal inside a @jit function "
                    "concretizes a traced value",
                    node,
                )
        if (
            self.fn_depth == 0
            and self.hot
            and name is not None
            and name.split(".", 1)[0] in _JNP_ALIASES
        ):
            attr = name.split(".", 1)[1] if "." in name else ""
            if attr not in _JNP_DTYPE_NAMES:
                self._emit(
                    "import-time-jnp",
                    f"{name}() at module import time in a hot module "
                    "initializes the device backend as an import side "
                    "effect",
                    node,
                )
        self.generic_visit(node)

    def visit_Expr(self, node: ast.Expr) -> None:
        if isinstance(node.value, ast.Call):
            name = _dotted(node.value.func)
            bare_sync = name == "jax.device_get" or (
                isinstance(node.value.func, ast.Attribute)
                and node.value.func.attr == "block_until_ready"
            )
            if bare_sync:
                self._emit(
                    "bare-sync",
                    "bare device sync (result discarded) outside bench/ "
                    "and tests/",
                    node,
                )
        self.generic_visit(node)


def _is_hot(rel_path: str) -> bool:
    parts = Path(rel_path).parts
    return len(parts) >= 2 and parts[0] == "protocol_tpu" and parts[1] in HOT_TREES


def scan_file(path: Path, root: Path) -> list[Finding]:
    rel = str(path.relative_to(root))
    try:
        tree = ast.parse(path.read_text(), filename=rel)
    except SyntaxError as exc:
        return [
            Finding(
                pass_name="ast",
                rule="syntax-error",
                severity="error",
                message=str(exc),
                file=rel,
                line=exc.lineno,
            )
        ]
    visitor = _Visitor(rel, hot=_is_hot(rel))
    visitor.visit(tree)
    return visitor.findings


def run_ast_pass(root: str | Path | None = None) -> tuple[list[Finding], int]:
    """Scan ``protocol_tpu/`` under ``root`` (default: the repo this
    package was imported from).  Returns ``(findings, files_scanned)``."""
    if root is None:
        root = Path(__file__).resolve().parent.parent.parent
    root = Path(root)
    findings: list[Finding] = []
    files = sorted((root / "protocol_tpu").rglob("*.py"))
    for path in files:
        findings.extend(scan_file(path, root))
    return findings, len(files)


__all__ = ["HOT_TREES", "run_ast_pass", "scan_file"]
