"""Seeded violation fixtures — kernels that deliberately break one
invariant each, so the analyzer itself is testable.

Every fixture pairs a tiny step function with a budget it violates;
``run_fixture`` traces and checks it exactly like a real backend, and
``tests/test_analysis.py`` asserts the right rule fires with the right
``file:line`` (the violating lines carry ``# VIOLATION: <name>``
markers the test resolves against this file).  The CLI exposes them as
``python -m protocol_tpu.analysis --fixture <name>`` (exits non-zero),
which doubles as a self-check that the gate can actually fail.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable

from .budget import GatherBudget, KernelBudget
from .invariants import TraceCase, check_case
from .report import Finding


@dataclass(frozen=True)
class Fixture:
    name: str
    rule: str  # the finding rule this fixture must trigger
    #: jaxpr fixtures return ``(budget, case)`` for ``check_case``; ast
    #: fixtures return ``(source, rel_path)`` for ``scan_source`` —
    #: violating code lives in strings, never as real module code, so
    #: the fixture file itself stays clean under the repo-wide pass.
    build: Callable[[], tuple]
    #: Marker suffix of the ``# VIOLATION:`` comment anchoring the
    #: expected finding line; None when the finding has no source site.
    marker: str | None
    #: Which analyzer pass evaluates this fixture.
    kind: str = "jaxpr"


def _extra_gather() -> tuple[KernelBudget, TraceCase]:
    import jax
    import jax.numpy as jnp
    import numpy as np

    t = jnp.asarray(np.arange(8, dtype=np.float32))
    idx = jnp.asarray(np.array([3, 1, 2], np.int32))

    def step(t, idx):
        a = t[idx]
        b = t[idx + 1]  # VIOLATION: extra-gather
        return a + b

    jaxpr = jax.make_jaxpr(step)(t, idx)
    budget = KernelBudget(backend="fixture:extra-gather", max_random_gathers=1)
    return budget, TraceCase("fixture:extra-gather", jaxpr)


def _f64_leak() -> tuple[KernelBudget, TraceCase]:
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental import enable_x64

    def step(t):
        wide = t.astype(jnp.float64)  # VIOLATION: f64-leak
        return wide * 2.0

    with enable_x64():
        jaxpr = jax.make_jaxpr(step)(np.ones(4, np.float32))
    budget = KernelBudget(backend="fixture:f64-leak", max_random_gathers=0)
    return budget, TraceCase("fixture:f64-leak", jaxpr)


def _callback_in_jit() -> tuple[KernelBudget, TraceCase]:
    import jax
    import jax.numpy as jnp
    import numpy as np

    def host_sum(x):
        return np.float32(np.asarray(x).sum())

    def step(t):
        out = jax.ShapeDtypeStruct((), jnp.float32)
        s = jax.pure_callback(host_sum, out, t)  # VIOLATION: callback-in-jit
        return t * s

    jaxpr = jax.make_jaxpr(step)(jnp.ones(4, jnp.float32))
    budget = KernelBudget(backend="fixture:callback-in-jit", max_random_gathers=0)
    return budget, TraceCase("fixture:callback-in-jit", jaxpr)


def _unsorted_boundary() -> tuple[KernelBudget, TraceCase]:
    import jax
    import jax.numpy as jnp
    import numpy as np

    hi = jnp.asarray(np.arange(32, dtype=np.float32))
    seg_end = jnp.asarray(np.array([3, 7, 12, 19, 25, 31], np.int32))

    def step(hi, seg_end):
        cum2 = jnp.stack([hi, hi], axis=-1)
        # The bridge's boundary read without the streaming declaration
        # (indices_are_sorted/unique_indices) — XLA plans a random read.
        ends = cum2[seg_end]  # VIOLATION: unsorted-boundary
        return ends[:, 0] + ends[:, 1]

    jaxpr = jax.make_jaxpr(step)(hi, seg_end)
    budget = KernelBudget(
        backend="fixture:unsorted-boundary",
        max_random_gathers=4,
        gather_budgets=(
            GatherBudget(dim="n_segments", max_total=4, max_random=4, boundary_sorted=True),
        ),
    )
    return budget, TraceCase(
        "fixture:unsorted-boundary", jaxpr, dims={"n_segments": 6}
    )


def _scatter_in_step() -> tuple[KernelBudget, TraceCase]:
    import jax
    import jax.numpy as jnp
    import numpy as np

    t = jnp.asarray(np.ones(4, np.float32))
    idx = jnp.asarray(np.array([2, 0, 3, 1], np.int32))

    def step(t, idx):
        return jnp.zeros(8, jnp.float32).at[idx].add(t)  # VIOLATION: scatter-in-step

    jaxpr = jax.make_jaxpr(step)(t, idx)
    budget = KernelBudget(
        backend="fixture:scatter-in-step", max_random_gathers=4, max_scatters=0
    )
    return budget, TraceCase("fixture:scatter-in-step", jaxpr)


def _missing_donation() -> tuple[KernelBudget, TraceCase]:
    import jax
    import jax.numpy as jnp

    @jax.jit  # declares no donate_argnames — the aliasing never lowers
    def undonated(t0):
        return t0 * 2.0

    arg = jnp.ones(4, jnp.float32)
    jaxpr = jax.make_jaxpr(undonated)(arg)
    budget = KernelBudget(
        backend="fixture:missing-donation",
        max_random_gathers=0,
        donated_args=("t0",),
    )
    return budget, TraceCase(
        "fixture:missing-donation",
        jaxpr,
        lowered_text=undonated.lower(arg).as_text(),
    )


#: Pass-3 seeded violations (observability-boundary rules).  The source
#: lives in strings so the AST pass over the real tree never sees it;
#: the fake paths place them in a hot/kernel tree so tree-scoped rules
#: apply exactly as they would to real code.
_TIME_IN_JIT_SRC = '''\
import time

import jax


@jax.jit
def step(t):
    t0 = time.perf_counter()  # VIOLATION: time-in-jit
    return t * 2.0, t0
'''


def _time_in_jit() -> tuple[str, str]:
    return _TIME_IN_JIT_SRC, "protocol_tpu/trust/_fixture_time_in_jit.py"


_LOGGING_IN_JIT_SRC = '''\
import logging

import jax

log = logging.getLogger(__name__)


@jax.jit
def step(t):
    log.info("converged to %s", t)  # VIOLATION: logging-in-jit
    return t * 2.0
'''


def _logging_in_jit() -> tuple[str, str]:
    return _LOGGING_IN_JIT_SRC, "protocol_tpu/trust/_fixture_logging_in_jit.py"


_CLOCK_IN_KERNEL_SRC = '''\
import time  # VIOLATION: clock-in-kernel-tree


def rowsum_probe(x):
    return time.monotonic(), x
'''


def _clock_in_kernel_tree() -> tuple[str, str]:
    return _CLOCK_IN_KERNEL_SRC, "protocol_tpu/ops/_fixture_clock_in_kernel.py"


_PLAN_MUTATION_SRC = '''\
import jax


def make_step(plan, fingerprint):
    @jax.jit
    def step(t, inserts, deletes):
        # Delta application belongs in the host stage, pre-dispatch;
        # under a trace it runs once at trace time and the kernel then
        # serves a stale layout forever after.
        new_plan = plan.apply_delta(inserts, deletes, fingerprint=fingerprint)  # VIOLATION: plan-mutation-in-converge
        return t * 2.0, new_plan

    return step
'''


def _plan_mutation_in_converge() -> tuple[str, str]:
    return _PLAN_MUTATION_SRC, "protocol_tpu/trust/_fixture_plan_mutation.py"


_JOURNAL_IN_JIT_SRC = '''\
import jax

from protocol_tpu.obs.journal import JOURNAL


@jax.jit
def step(t):
    # Under a trace this records ONE event at trace time and never
    # again — the flight recorder would replay a stale line forever.
    JOURNAL.record("iteration", residual=t)  # VIOLATION: journal-write-in-jit
    return t * 2.0
'''


def _journal_write_in_jit() -> tuple[str, str]:
    return _JOURNAL_IN_JIT_SRC, "protocol_tpu/trust/_fixture_journal_in_jit.py"


_BLOCKING_INGEST_SRC = '''\
import queue

from protocol_tpu.obs import metrics as obs_metrics

PENDING = queue.Queue(maxsize=4)


def observe_depth():
    # Keeps this fixture single-purpose: pass 10's unobserved-queue
    # rule is satisfied so only the pass-6 rules below fire.
    obs_metrics.PIPELINE_QUEUE_DEPTH.set(PENDING.qsize())


def device_stage(manager, atts, prepared):
    # The epoch loop verifying signatures re-couples convergence
    # cadence to ingest load — admission belongs in the ingest plane.
    results = manager.add_attestations_bulk(atts)  # VIOLATION: blocking-ingest-in-epoch-loop
    # An unbounded blocking put can park the epoch loop forever when
    # the consumer stalls; put_nowait (coalescing) or timeout= are the
    # sanctioned shapes.
    PENDING.put(prepared)
    return results
'''


def _blocking_ingest_in_epoch_loop() -> tuple[str, str]:
    # The fake path lands on an epoch-loop file so the file-scoped
    # pass-6 rule applies exactly as it would to the real module.
    return _BLOCKING_INGEST_SRC, "protocol_tpu/node/pipeline.py"


_BLOCKING_PROVE_SRC = '''\
def device_stage(manager, prepared):
    # A synchronous SNARK on the epoch path re-serializes seconds of
    # whole-core proving into the epoch cadence — the coupling the
    # async proving plane (protocol_tpu/prover/) exists to remove.
    manager.calculate_proofs(prepared.epoch)  # VIOLATION: blocking-prove-in-epoch-loop
    return prepared
'''


def _blocking_prove_in_epoch_loop() -> tuple[str, str]:
    # Same file-scoped shape as pass 6: the fake path lands on an
    # epoch-loop file so the pass-9 rule applies exactly as it would
    # to the real module.
    return _BLOCKING_PROVE_SRC, "protocol_tpu/node/pipeline.py"


_UNOBSERVED_QUEUE_SRC = '''\
import queue


class Stage:
    def __init__(self):
        # A bounded queue is a backpressure point; without a depth
        # gauge in this file, "the stage is saturated" is a guess
        # instead of a scrape.
        self._queue = queue.Queue(maxsize=8)  # VIOLATION: unobserved-queue

    def push(self, item):
        self._queue.put_nowait(item)
'''


def _unobserved_queue() -> tuple[str, str]:
    return _UNOBSERVED_QUEUE_SRC, "protocol_tpu/ingest/_fixture_unobserved_queue.py"


_NON_ATOMIC_STATE_WRITE_SRC = '''\
import json


def persist_cursor(path, cursor):
    # Durable node state through a bare open(): a crash mid-write tears
    # the file and the next boot reads garbage — the checkpoint store's
    # _atomic_write (tmp + fsync + rename) is the sanctioned shape, or
    # an append path that fsyncs what it wrote (node/wal.py).
    with open(path, "w") as f:  # VIOLATION: non-atomic-state-write
        json.dump({"cursor": cursor}, f)
'''


def _non_atomic_state_write() -> tuple[str, str]:
    # The fake path lands in node/ so the tree-scoped pass-11 rule
    # applies exactly as it would to real node state code.
    return _NON_ATOMIC_STATE_WRITE_SRC, "protocol_tpu/node/_fixture_state_write.py"


_FAULT_POINT_IN_JIT_SRC = '''\
import jax

from protocol_tpu import chaos


@jax.jit
def step(t):
    # Under a trace this hook fires ONCE at trace time and never again:
    # the chaos schedule silently stops covering the point, and a
    # callback-shaped rewrite would smuggle a host sync into the hot
    # loop — fault points live at host boundaries, like spans and
    # journal writes.
    chaos.fire("epoch.post_converge")  # VIOLATION: fault-point-in-jit
    return t * 2.0
'''


def _fault_point_in_jit() -> tuple[str, str]:
    return _FAULT_POINT_IN_JIT_SRC, "protocol_tpu/trust/_fixture_chaos_in_jit.py"


#: Pass-7 seeded violations (whole-program concurrency rules).  Each
#: source is a self-contained "program": it declares its own thread
#: roots, so the analyzer's reachability machinery runs exactly as it
#: does on the real tree.  Paths land outside the thread-confined
#: trees so the shared-state rules apply.
_UNGUARDED_SHARED_ATTR_SRC = '''\
import threading


class Tally:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0

    def bump(self):
        with self._lock:
            self.count += 1

    def read(self):
        return self.count  # VIOLATION: unguarded-shared-attr


def run():
    t = Tally()
    threading.Thread(target=t.bump).start()
    threading.Thread(target=t.read).start()
'''


def _unguarded_shared_attr() -> tuple[str, str]:
    return _UNGUARDED_SHARED_ATTR_SRC, "protocol_tpu/node/_fixture_shared_attr.py"


_UNGUARDED_RMW_SRC = '''\
import threading


class Hits:
    def __init__(self):
        self.n = 0

    def work(self):
        self.n += 1  # VIOLATION: unguarded-rmw


def run():
    h = Hits()
    threading.Thread(target=h.work, name="w1").start()
    threading.Thread(target=h.work, name="w2").start()
'''


def _unguarded_rmw() -> tuple[str, str]:
    return _UNGUARDED_RMW_SRC, "protocol_tpu/obs/_fixture_rmw.py"


_CHECK_THEN_ACT_SRC = '''\
import threading


class Once:
    def __init__(self):
        self.started = False

    def boot(self):
        if not self.started:
            self.started = True  # VIOLATION: check-then-act


def run():
    o = Once()
    threading.Thread(target=o.boot, name="a").start()
    threading.Thread(target=o.boot, name="b").start()
'''


def _check_then_act() -> tuple[str, str]:
    return _CHECK_THEN_ACT_SRC, "protocol_tpu/ingest/_fixture_check_act.py"


_LOCK_ORDER_CYCLE_SRC = '''\
import threading


class Transfer:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def ab(self):
        with self._a:
            with self._b:  # VIOLATION: lock-order-cycle
                pass

    def ba(self):
        with self._b:
            with self._a:
                pass
'''


def _lock_order_cycle() -> tuple[str, str]:
    return _LOCK_ORDER_CYCLE_SRC, "protocol_tpu/node/_fixture_lock_order.py"


_BLOCKING_UNDER_LOCK_SRC = '''\
import queue
import threading


class Stage:
    def __init__(self):
        self._lock = threading.Lock()
        self._queue = queue.Queue(maxsize=4)

    def push(self, item):
        with self._lock:
            self._queue.put(item)  # VIOLATION: blocking-call-under-lock
'''


def _blocking_call_under_lock() -> tuple[str, str]:
    return _BLOCKING_UNDER_LOCK_SRC, "protocol_tpu/ingest/_fixture_block_lock.py"


_NATIVE_UNDER_LOCK_SRC = '''\
import threading

from protocol_tpu.crypto import native as cnative


class Verifier:
    def __init__(self):
        self._lock = threading.Lock()

    def check(self, sigs):
        with self._lock:
            return cnative.eddsa_verify_batch(sigs)  # VIOLATION: native-call-under-lock
'''


def _native_call_under_lock() -> tuple[str, str]:
    return _NATIVE_UNDER_LOCK_SRC, "protocol_tpu/node/_fixture_native_lock.py"


#: Pass-8 seeded violations (SPMD-lowering comm rules).  Each fixture
#: compiles a REAL module through the real jit/partitioner path under
#: the 8-device CPU mesh and pairs it with a CommBudget it violates;
#: the finding anchors through jax's HLO source metadata back to the
#: ``# VIOLATION:`` line below — the same file:line contract as the
#: jaxpr fixtures.


def _comm_mesh():
    from ..parallel.mesh import SHARD_AXIS, default_mesh

    return default_mesh(), SHARD_AXIS


def _comm_case(backend, fn, args, dims, arg_names=(), donate=()):
    """Compile ``fn`` and wrap it as a CommCase (jaxpr psums counted
    from the same trace the module was lowered from; the buffer
    assignment rides along for the pass-12 fixtures)."""
    import jax

    from .comm.lowering import CommCase, _mem_stats
    from .jaxpr_walk import PSUM_PRIMITIVES, collect_primitives

    compiled = jax.jit(fn, donate_argnames=tuple(donate)).lower(*args).compile()
    jaxpr = jax.make_jaxpr(fn)(*args)
    return CommCase(
        backend=backend,
        dims=dims,
        module_text=compiled.as_text(),
        arg_names=tuple(arg_names),
        jaxpr_psums=len(collect_primitives(jaxpr, PSUM_PRIMITIVES)),
        mem=_mem_stats(compiled),
    )


def _surprise_all_gather():
    from functools import partial

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from ..parallel.sharded import _shard_map
    from .budget import CollectiveBudget, CommBudget

    mesh, axis = _comm_mesh()
    n_shards = mesh.shape[axis]
    v = jax.device_put(
        np.ones(64 * n_shards, np.float32), NamedSharding(mesh, P(axis))
    )

    @partial(_shard_map, mesh=mesh, in_specs=P(axis), out_specs=P())
    def step(local):
        # The partitioner-surprise anti-pattern: re-materializing the
        # full edge slice on every shard before reducing.
        full = lax.all_gather(local, "shard", tiled=True)  # VIOLATION: surprise-all-gather
        return lax.psum(jnp.sum(full), "shard")

    budget = CommBudget(
        backend="fixture:surprise-all-gather",
        collectives=(CollectiveBudget(kind="all-reduce", max_count=1),),
        bytes_const=1 << 20,
    )
    case = _comm_case(
        "fixture:surprise-all-gather", step, (v,),
        dims={"n": 64, "n_shards": n_shards},
    )
    return budget, [case]


def _comm_bytes_over_budget():
    from functools import partial

    import jax
    import numpy as np
    from jax import lax
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from ..parallel.sharded import _shard_map
    from .budget import CollectiveBudget, CommBudget

    mesh, axis = _comm_mesh()
    n_shards = mesh.shape[axis]
    n, e = 64, 4096
    v = jax.device_put(np.ones(e, np.float32), NamedSharding(mesh, P()))

    @partial(_shard_map, mesh=mesh, in_specs=P(), out_specs=P())
    def step(acc):
        # An O(E) psum inside the iteration loop: every step ships the
        # whole edge-sized vector over the wire instead of the N-sized
        # boundary completion.
        return lax.psum(acc, "shard") / n_shards  # VIOLATION: comm-bytes-over-budget

    def run(v):
        return lax.fori_loop(0, 4, lambda i, acc: step(acc), v)

    budget = CommBudget(
        backend="fixture:comm-bytes-over-budget",
        collectives=(CollectiveBudget(kind="all-reduce", max_count=1),),
        bytes_n=8.0,  # O(N) allowance only — E-sized traffic must trip
    )
    case = _comm_case(
        "fixture:comm-bytes-over-budget", run, (v,),
        dims={"n": n, "edges": e, "n_shards": n_shards},
    )
    return budget, [case]


def _host_round_trip():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from .budget import CommBudget

    def host_sum(x):
        return np.float32(np.asarray(x).sum())

    def step(t):
        out = jax.ShapeDtypeStruct((), jnp.float32)
        s = jax.pure_callback(host_sum, out, t)  # VIOLATION: host-round-trip
        return t * s

    budget = CommBudget(
        backend="fixture:host-round-trip", max_host_round_trips=0
    )
    case = _comm_case(
        "fixture:host-round-trip", step, (jnp.ones(8, jnp.float32),),
        dims={"n": 8, "n_shards": 1},
    )
    return budget, [case]


def _alias_dropped():
    import jax.numpy as jnp

    from .budget import CommBudget

    def undonated(t0):  # no donate_argnames — the alias never lowers
        return t0 * 2.0

    budget = CommBudget(
        backend="fixture:alias-dropped", donated_args=("t0",)
    )
    case = _comm_case(
        "fixture:alias-dropped", undonated, (jnp.ones(4, jnp.float32),),
        dims={"n": 4, "n_shards": 1}, arg_names=("t0",),
    )
    return budget, [case]


def _psum_lowering_mismatch():
    from functools import partial

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from ..parallel.sharded import _shard_map
    from .budget import CollectiveBudget, CommBudget

    mesh, axis = _comm_mesh()
    v = jax.device_put(
        np.arange(16, dtype=np.float32), NamedSharding(mesh, P(axis))
    )

    @partial(_shard_map, mesh=mesh, in_specs=P(axis), out_specs=P())
    def step(local):
        # The dead psum survives the jaxpr but DCE strips it from the
        # compiled module: the jaxpr now LIES about the wire — exactly
        # the jaxpr-vs-lowering drift the cross-check exists to catch.
        dead = lax.psum(jnp.sum(local * 2.0), "shard")  # noqa: F841
        return lax.psum(jnp.sum(local), "shard")  # VIOLATION: psum-lowering-mismatch

    budget = CommBudget(
        backend="fixture:psum-lowering-mismatch",
        collectives=(CollectiveBudget(kind="all-reduce", max_count=2),),
        bytes_const=1 << 20,
    )
    case = _comm_case(
        "fixture:psum-lowering-mismatch", step, (v,),
        dims={"n": 16, "n_shards": mesh.shape[axis]},
    )
    return budget, [case]


#: Pass-12 seeded violations (peak-HBM rules).  The lowering fixtures
#: compile REAL modules through the real jit path and judge their
#: buffer assignment against a MemBudget they violate; anchored
#: fixtures resolve through the largest-temp / host-transfer HLO
#: metadata back to the ``# VIOLATION:`` line, the same file:line
#: contract as the comm fixtures.  The AST fixtures ride source
#: strings scanned with the memory rules armed (kind="mem-ast").


def _o_e_live_temporary():
    import jax.numpy as jnp
    import numpy as np

    from .budget import MemBudget

    n, e = 512, 4096
    src = jnp.asarray(np.arange(e, dtype=np.int32) % n)
    w = jnp.asarray(np.ones(e, np.float32))
    t = jnp.asarray(np.ones(n, np.float32))

    def step(src, w, t):
        # The anti-pattern the transient budget exists to forbid: a
        # full edge-sized contribution vector held live across two
        # reductions instead of streamed through the fused pipeline.
        contrib = w * t[src]  # VIOLATION: o-e-live-temporary
        return jnp.sum(contrib) + jnp.sum(contrib * contrib)

    budget = MemBudget(
        backend="fixture:o-e-live-temporary",
        resident_edge_bytes=8.0,  # src + w are legal resident inputs
        resident_n=8.0,
        resident_const=4096.0,
        transient_n=8.0,  # N-linear only: the E-sized temp must trip
        transient_const=1024.0,
    )
    case = _comm_case(
        "fixture:o-e-live-temporary", step, (src, w, t),
        dims={"n": n, "edges": e, "n_shards": 1},
    )
    return budget, [case]


def _donation_peak_doubled():
    import jax.numpy as jnp

    from .budget import MemBudget

    def undonated(t0, p):  # no donate_argnames — the alias never lowers
        return t0 * 0.9 + p * 0.1

    budget = MemBudget(
        backend="fixture:donation-peak-doubled",
        resident_n=16.0,
        resident_const=4096.0,
        transient_n=64.0,  # generous: only the donation rule may fire
        transient_const=65536.0,
        donated_args=("t0",),
    )
    n = 1024
    case = _comm_case(
        "fixture:donation-peak-doubled", undonated,
        (jnp.ones(n, jnp.float32), jnp.ones(n, jnp.float32)),
        dims={"n": n, "n_shards": 1}, arg_names=("t0", "p"),
    )
    return budget, [case]


def _shard_replicated_edges():
    from functools import partial

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from ..parallel.sharded import _shard_map
    from .budget import MemBudget

    mesh, axis = _comm_mesh()
    n_shards = mesh.shape[axis]
    n, e = 64, 8192
    # The regression ROADMAP item 1 cannot afford: the edge-sized
    # operand REPLICATED onto every shard instead of partitioned —
    # per-device resident holds all E entries, not E/n_shards.
    w = jax.device_put(np.ones(e, np.float32), NamedSharding(mesh, P()))

    @partial(_shard_map, mesh=mesh, in_specs=P(), out_specs=P())
    def step(w_full):  # VIOLATION: shard-replicated-edges
        return lax.psum(jnp.sum(w_full), "shard") / n_shards

    budget = MemBudget(
        backend="fixture:shard-replicated-edges",
        resident_edge_bytes=4.0,  # f32 edge weights, PER SHARD
        resident_n=16.0,
        resident_const=4096.0,
        transient_n=64.0,
        transient_const=65536.0,
    )
    case = _comm_case(
        "fixture:shard-replicated-edges", step, (w,),
        dims={"n": n, "edges": e, "n_shards": n_shards},
    )
    return budget, [case]


def _host_staging_over_cap():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from .budget import MemBudget

    n, e = 64, 8192

    def host_norm(x):
        return np.float32(np.abs(np.asarray(x)).sum())

    def step(edges):
        out = jax.ShapeDtypeStruct((), jnp.float32)
        # An O(E) operand shipped through a host callback: the staging
        # cap (O(N) bytes) exists to keep edge-scale data on-device.
        s = jax.pure_callback(host_norm, out, edges)  # VIOLATION: host-staging-over-cap
        return edges * s

    budget = MemBudget(
        backend="fixture:host-staging-over-cap",
        resident_edge_bytes=4.0,
        resident_const=4096.0,
        transient_n=64.0,
        transient_const=65536.0,
        staging_n=4.0,  # an f32[N] scalar reduction would be fine
    )
    case = _comm_case(
        "fixture:host-staging-over-cap", step,
        (jnp.ones(e, jnp.float32),),
        dims={"n": n, "edges": e, "n_shards": 1},
    )
    return budget, [case]


_HOST_MATERIALIZATION_SRC = '''\
import numpy as np


def device_stage(manager, prepared, plan):
    # Materializing an edge-scale plan column on the host per tick:
    # O(E) bytes copied device->host inside the epoch cadence — edge
    # host work belongs in plan build (Manager.prepare_epoch).
    seg_dst = np.asarray(plan.seg_dst)  # VIOLATION: host-materialization-of-edges
    return seg_dst.shape[0]
'''


def _host_materialization_of_edges() -> tuple[str, str]:
    # The fake path lands on an epoch-loop file so the file-scoped
    # pass-12 rule applies exactly as it would to the real module.
    return _HOST_MATERIALIZATION_SRC, "protocol_tpu/node/pipeline.py"


_UNBOUNDED_CACHE_SRC = '''\
class ScoreServer:
    """A long-lived node class with an epoch-keyed cache that only
    ever grows — the leak the ring-eviction doctrine exists to stop
    (4 MB of f32[N] scores per epoch at 1M peers)."""

    def __init__(self):
        self._score_cache = {}  # VIOLATION: unbounded-cache-growth

    def publish(self, epoch, scores):
        self._score_cache[epoch] = scores

    def serve(self, epoch):
        return self._score_cache.get(epoch)
'''


def _unbounded_cache_growth() -> tuple[str, str]:
    return _UNBOUNDED_CACHE_SRC, "protocol_tpu/node/_fixture_cache_growth.py"


# -- pass-13 determinism fixtures -------------------------------------------

_SET_ORDER_TO_STATE_SRC = '''\
import numpy as np


def stamp_columns(peers, scores):
    # A peer *set* flattened straight into a checkpoint column: the
    # array inherits per-process hash order, so two hosts digest
    # different bytes from identical peer sets.
    live = {p for p in peers if p >= 0}
    column = np.asarray(list(live))  # VIOLATION: set-order-to-state
    return column, scores[column]
'''


def _set_order_to_state() -> tuple[str, str]:
    return _SET_ORDER_TO_STATE_SRC, "protocol_tpu/node/_fixture_set_order.py"


_UNSORTED_DIRSCAN_SRC = '''\
import os


def replay_segments(wal_dir):
    # WAL segments replayed in directory-scan order: inode history
    # decides the replay sequence, so two hosts reconverge through
    # different intermediate states.
    names = os.listdir(wal_dir)  # VIOLATION: unsorted-dirscan
    return [os.path.join(wal_dir, n) for n in names]
'''


def _unsorted_dirscan() -> tuple[str, str]:
    return _UNSORTED_DIRSCAN_SRC, "protocol_tpu/node/_fixture_dirscan.py"


_HASH_ORDERING_SRC = '''\
def partition_key(sender_pk, n_partitions):
    # Builtin hash() as a partition key: hash(str) is salted per
    # process (PYTHONHASHSEED), so the same sender lands on different
    # partitions on different hosts.
    return hash(sender_pk) % n_partitions  # VIOLATION: hash-ordering
'''


def _hash_ordering() -> tuple[str, str]:
    return _HASH_ORDERING_SRC, "protocol_tpu/ingest/_fixture_hash_key.py"


_UNSEEDED_RNG_SRC = '''\
import numpy as np


def churn_draw(n_peers):
    # A churn-stream draw from the process-global RNG: every host
    # samples a different peer set, so the epoch graphs diverge
    # before the first matvec.
    return np.random.permutation(n_peers)  # VIOLATION: unseeded-rng
'''


def _unseeded_rng() -> tuple[str, str]:
    return _UNSEEDED_RNG_SRC, "protocol_tpu/models/_fixture_churn_rng.py"


_CLOCK_IN_DIGEST_SRC = '''\
import hashlib
import time


def seal_manifest(columns):
    # Wall clock folded into the manifest digest: the seal differs on
    # every host and every replay, so bit-identity verification can
    # never pass.
    stamp = time.time()
    h = hashlib.sha256(str(columns).encode())
    h.update(str(stamp).encode())  # VIOLATION: clock-in-digest
    return h.hexdigest()
'''


def _clock_in_digest() -> tuple[str, str]:
    return _CLOCK_IN_DIGEST_SRC, "protocol_tpu/node/_fixture_clock_seal.py"


def _hlo_nondeterministic_compile() -> tuple[str, str, str]:
    # Two "compiles" of the same entry that differ structurally after
    # canonicalization: identical SSA naming-counter noise (different
    # value numbers, same shape) cancels out, but compile 2 fuses an
    # extra multiply — the drift the double-compile cross-check exists
    # to catch.
    module_a = """\
HloModule converge_fixture

ENTRY %main.12 {
  %param.0 = f32[64]{0} parameter(0)
  %param.1 = f32[64]{0} parameter(1)
  %add.3 = f32[64]{0} add(%param.0, %param.1)
  ROOT %mul.4 = f32[64]{0} multiply(%add.3, %param.1)
}
"""
    module_b = """\
HloModule converge_fixture

ENTRY %main.47 {
  %param.8 = f32[64]{0} parameter(0)
  %param.9 = f32[64]{0} parameter(1)
  %mul.13 = f32[64]{0} multiply(%param.8, %param.9)
  %add.11 = f32[64]{0} add(%mul.13, %param.9)
  ROOT %mul.14 = f32[64]{0} multiply(%add.11, %param.9)
}
"""
    return "fixture:hlo-drift", module_a, module_b


FIXTURES: dict[str, Fixture] = {
    f.name: f
    for f in (
        Fixture("extra-gather", "gather-budget", _extra_gather, "extra-gather"),
        Fixture("f64-leak", "f64-dtype", _f64_leak, "f64-leak"),
        Fixture(
            "callback-in-jit", "callback-in-jit", _callback_in_jit, "callback-in-jit"
        ),
        Fixture(
            "unsorted-boundary",
            "boundary-sorted",
            _unsorted_boundary,
            "unsorted-boundary",
        ),
        Fixture(
            "scatter-in-step", "scatter-budget", _scatter_in_step, "scatter-in-step"
        ),
        Fixture(
            "missing-donation", "donation-not-materialized", _missing_donation, None
        ),
        Fixture(
            "time-in-jit", "host-clock-in-jit", _time_in_jit, "time-in-jit",
            kind="ast",
        ),
        Fixture(
            "logging-in-jit", "logging-in-jit", _logging_in_jit,
            "logging-in-jit", kind="ast",
        ),
        Fixture(
            "clock-in-kernel-tree", "clock-in-kernel-tree",
            _clock_in_kernel_tree, "clock-in-kernel-tree", kind="ast",
        ),
        Fixture(
            "plan-mutation-in-converge", "plan-mutation-in-converge",
            _plan_mutation_in_converge, "plan-mutation-in-converge",
            kind="ast",
        ),
        Fixture(
            "journal-write-in-jit", "journal-write-in-jit",
            _journal_write_in_jit, "journal-write-in-jit",
            kind="ast",
        ),
        Fixture(
            "blocking-ingest-in-epoch-loop", "blocking-ingest-in-epoch-loop",
            _blocking_ingest_in_epoch_loop, "blocking-ingest-in-epoch-loop",
            kind="ast",
        ),
        Fixture(
            "blocking-prove-in-epoch-loop", "blocking-prove-in-epoch-loop",
            _blocking_prove_in_epoch_loop, "blocking-prove-in-epoch-loop",
            kind="ast",
        ),
        Fixture(
            "unobserved-queue", "unobserved-queue",
            _unobserved_queue, "unobserved-queue", kind="ast",
        ),
        Fixture(
            "non-atomic-state-write", "non-atomic-state-write",
            _non_atomic_state_write, "non-atomic-state-write", kind="ast",
        ),
        Fixture(
            "fault-point-in-jit", "fault-point-in-jit",
            _fault_point_in_jit, "fault-point-in-jit", kind="ast",
        ),
        Fixture(
            "unguarded-shared-attr", "unguarded-shared-attr",
            _unguarded_shared_attr, "unguarded-shared-attr",
            kind="concurrency",
        ),
        Fixture(
            "unguarded-rmw", "unguarded-rmw", _unguarded_rmw,
            "unguarded-rmw", kind="concurrency",
        ),
        Fixture(
            "check-then-act", "check-then-act", _check_then_act,
            "check-then-act", kind="concurrency",
        ),
        Fixture(
            "lock-order-cycle", "lock-order-cycle", _lock_order_cycle,
            "lock-order-cycle", kind="concurrency",
        ),
        Fixture(
            "blocking-call-under-lock", "blocking-call-under-lock",
            _blocking_call_under_lock, "blocking-call-under-lock",
            kind="concurrency",
        ),
        Fixture(
            "native-call-under-lock", "native-call-under-lock",
            _native_call_under_lock, "native-call-under-lock",
            kind="concurrency",
        ),
        Fixture(
            "surprise-all-gather", "collective-kind",
            _surprise_all_gather, "surprise-all-gather", kind="comm",
        ),
        Fixture(
            "comm-bytes-over-budget", "comm-bytes-budget",
            _comm_bytes_over_budget, "comm-bytes-over-budget", kind="comm",
        ),
        Fixture(
            "host-round-trip", "host-round-trip",
            _host_round_trip, "host-round-trip", kind="comm",
        ),
        Fixture(
            "alias-dropped", "alias-dropped", _alias_dropped, None,
            kind="comm",
        ),
        Fixture(
            "psum-lowering-mismatch", "psum-lowering-mismatch",
            _psum_lowering_mismatch, "psum-lowering-mismatch", kind="comm",
        ),
        Fixture(
            "o-e-live-temporary", "o-e-live-temporary",
            _o_e_live_temporary, "o-e-live-temporary", kind="mem",
        ),
        Fixture(
            "donation-peak-doubled", "donation-peak-doubled",
            _donation_peak_doubled, None, kind="mem",
        ),
        Fixture(
            "shard-replicated-edges", "shard-replicated-edges",
            _shard_replicated_edges, None, kind="mem",
        ),
        Fixture(
            "host-staging-over-cap", "host-staging-over-cap",
            _host_staging_over_cap, "host-staging-over-cap", kind="mem",
        ),
        Fixture(
            "host-materialization-of-edges", "host-materialization-of-edges",
            _host_materialization_of_edges, "host-materialization-of-edges",
            kind="mem-ast",
        ),
        Fixture(
            "unbounded-cache-growth", "unbounded-cache-growth",
            _unbounded_cache_growth, "unbounded-cache-growth",
            kind="mem-ast",
        ),
        Fixture(
            "set-order-to-state", "set-order-to-state",
            _set_order_to_state, "set-order-to-state", kind="det-ast",
        ),
        Fixture(
            "unsorted-dirscan", "unsorted-dirscan",
            _unsorted_dirscan, "unsorted-dirscan", kind="det-ast",
        ),
        Fixture(
            "hash-ordering", "hash-ordering",
            _hash_ordering, "hash-ordering", kind="det-ast",
        ),
        Fixture(
            "unseeded-rng", "unseeded-rng",
            _unseeded_rng, "unseeded-rng", kind="det-ast",
        ),
        Fixture(
            "clock-in-digest", "clock-in-digest",
            _clock_in_digest, "clock-in-digest", kind="det-ast",
        ),
        Fixture(
            "hlo-nondeterministic-compile", "hlo-nondeterministic-compile",
            _hlo_nondeterministic_compile, None, kind="det-hlo",
        ),
    )
}


def run_fixture(name: str) -> list[Finding]:
    """Trace and check one seeded violation; raises KeyError on an
    unknown name (the CLI lists valid ones)."""
    fixture = FIXTURES[name]
    if fixture.kind == "ast":
        from .ast_rules import scan_source

        source, rel_path = fixture.build()
        return scan_source(source, rel_path)
    if fixture.kind == "concurrency":
        from .concurrency import analyze_sources

        source, rel_path = fixture.build()
        return analyze_sources({rel_path: source})
    if fixture.kind == "comm":
        from .comm.checker import check_comm_case

        budget, cases = fixture.build()
        return [f for c in cases for f in check_comm_case(budget, c)[0]]
    if fixture.kind == "mem":
        from .memory.checker import check_mem_case

        budget, cases = fixture.build()
        return [f for c in cases for f in check_mem_case(budget, c)[0]]
    if fixture.kind == "mem-ast":
        from .ast_rules import scan_source

        source, rel_path = fixture.build()
        return scan_source(source, rel_path, mem_rules=True)
    if fixture.kind == "det-ast":
        from .determinism.ast_walk import scan_det_source

        source, rel_path = fixture.build()
        return scan_det_source(source, rel_path)
    if fixture.kind == "det-hlo":
        from .determinism.checker import check_recompile

        backend, module_a, module_b = fixture.build()
        return check_recompile(backend, module_a, module_b)
    budget, case = fixture.build()
    return check_case(budget, case)


__all__ = ["FIXTURES", "Fixture", "run_fixture"]
