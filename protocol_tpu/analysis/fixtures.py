"""Seeded violation fixtures — kernels that deliberately break one
invariant each, so the analyzer itself is testable.

Every fixture pairs a tiny step function with a budget it violates;
``run_fixture`` traces and checks it exactly like a real backend, and
``tests/test_analysis.py`` asserts the right rule fires with the right
``file:line`` (the violating lines carry ``# VIOLATION: <name>``
markers the test resolves against this file).  The CLI exposes them as
``python -m protocol_tpu.analysis --fixture <name>`` (exits non-zero),
which doubles as a self-check that the gate can actually fail.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable

from .budget import GatherBudget, KernelBudget
from .invariants import TraceCase, check_case
from .report import Finding


@dataclass(frozen=True)
class Fixture:
    name: str
    rule: str  # the finding rule this fixture must trigger
    #: jaxpr fixtures return ``(budget, case)`` for ``check_case``; ast
    #: fixtures return ``(source, rel_path)`` for ``scan_source`` —
    #: violating code lives in strings, never as real module code, so
    #: the fixture file itself stays clean under the repo-wide pass.
    build: Callable[[], tuple]
    #: Marker suffix of the ``# VIOLATION:`` comment anchoring the
    #: expected finding line; None when the finding has no source site.
    marker: str | None
    #: Which analyzer pass evaluates this fixture.
    kind: str = "jaxpr"


def _extra_gather() -> tuple[KernelBudget, TraceCase]:
    import jax
    import jax.numpy as jnp
    import numpy as np

    t = jnp.asarray(np.arange(8, dtype=np.float32))
    idx = jnp.asarray(np.array([3, 1, 2], np.int32))

    def step(t, idx):
        a = t[idx]
        b = t[idx + 1]  # VIOLATION: extra-gather
        return a + b

    jaxpr = jax.make_jaxpr(step)(t, idx)
    budget = KernelBudget(backend="fixture:extra-gather", max_random_gathers=1)
    return budget, TraceCase("fixture:extra-gather", jaxpr)


def _f64_leak() -> tuple[KernelBudget, TraceCase]:
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental import enable_x64

    def step(t):
        wide = t.astype(jnp.float64)  # VIOLATION: f64-leak
        return wide * 2.0

    with enable_x64():
        jaxpr = jax.make_jaxpr(step)(np.ones(4, np.float32))
    budget = KernelBudget(backend="fixture:f64-leak", max_random_gathers=0)
    return budget, TraceCase("fixture:f64-leak", jaxpr)


def _callback_in_jit() -> tuple[KernelBudget, TraceCase]:
    import jax
    import jax.numpy as jnp
    import numpy as np

    def host_sum(x):
        return np.float32(np.asarray(x).sum())

    def step(t):
        out = jax.ShapeDtypeStruct((), jnp.float32)
        s = jax.pure_callback(host_sum, out, t)  # VIOLATION: callback-in-jit
        return t * s

    jaxpr = jax.make_jaxpr(step)(jnp.ones(4, jnp.float32))
    budget = KernelBudget(backend="fixture:callback-in-jit", max_random_gathers=0)
    return budget, TraceCase("fixture:callback-in-jit", jaxpr)


def _unsorted_boundary() -> tuple[KernelBudget, TraceCase]:
    import jax
    import jax.numpy as jnp
    import numpy as np

    hi = jnp.asarray(np.arange(32, dtype=np.float32))
    seg_end = jnp.asarray(np.array([3, 7, 12, 19, 25, 31], np.int32))

    def step(hi, seg_end):
        cum2 = jnp.stack([hi, hi], axis=-1)
        # The bridge's boundary read without the streaming declaration
        # (indices_are_sorted/unique_indices) — XLA plans a random read.
        ends = cum2[seg_end]  # VIOLATION: unsorted-boundary
        return ends[:, 0] + ends[:, 1]

    jaxpr = jax.make_jaxpr(step)(hi, seg_end)
    budget = KernelBudget(
        backend="fixture:unsorted-boundary",
        max_random_gathers=4,
        gather_budgets=(
            GatherBudget(dim="n_segments", max_total=4, max_random=4, boundary_sorted=True),
        ),
    )
    return budget, TraceCase(
        "fixture:unsorted-boundary", jaxpr, dims={"n_segments": 6}
    )


def _scatter_in_step() -> tuple[KernelBudget, TraceCase]:
    import jax
    import jax.numpy as jnp
    import numpy as np

    t = jnp.asarray(np.ones(4, np.float32))
    idx = jnp.asarray(np.array([2, 0, 3, 1], np.int32))

    def step(t, idx):
        return jnp.zeros(8, jnp.float32).at[idx].add(t)  # VIOLATION: scatter-in-step

    jaxpr = jax.make_jaxpr(step)(t, idx)
    budget = KernelBudget(
        backend="fixture:scatter-in-step", max_random_gathers=4, max_scatters=0
    )
    return budget, TraceCase("fixture:scatter-in-step", jaxpr)


def _missing_donation() -> tuple[KernelBudget, TraceCase]:
    import jax
    import jax.numpy as jnp

    @jax.jit  # declares no donate_argnames — the aliasing never lowers
    def undonated(t0):
        return t0 * 2.0

    arg = jnp.ones(4, jnp.float32)
    jaxpr = jax.make_jaxpr(undonated)(arg)
    budget = KernelBudget(
        backend="fixture:missing-donation",
        max_random_gathers=0,
        donated_args=("t0",),
    )
    return budget, TraceCase(
        "fixture:missing-donation",
        jaxpr,
        lowered_text=undonated.lower(arg).as_text(),
    )


#: Pass-3 seeded violations (observability-boundary rules).  The source
#: lives in strings so the AST pass over the real tree never sees it;
#: the fake paths place them in a hot/kernel tree so tree-scoped rules
#: apply exactly as they would to real code.
_TIME_IN_JIT_SRC = '''\
import time

import jax


@jax.jit
def step(t):
    t0 = time.perf_counter()  # VIOLATION: time-in-jit
    return t * 2.0, t0
'''


def _time_in_jit() -> tuple[str, str]:
    return _TIME_IN_JIT_SRC, "protocol_tpu/trust/_fixture_time_in_jit.py"


_LOGGING_IN_JIT_SRC = '''\
import logging

import jax

log = logging.getLogger(__name__)


@jax.jit
def step(t):
    log.info("converged to %s", t)  # VIOLATION: logging-in-jit
    return t * 2.0
'''


def _logging_in_jit() -> tuple[str, str]:
    return _LOGGING_IN_JIT_SRC, "protocol_tpu/trust/_fixture_logging_in_jit.py"


_CLOCK_IN_KERNEL_SRC = '''\
import time  # VIOLATION: clock-in-kernel-tree


def rowsum_probe(x):
    return time.monotonic(), x
'''


def _clock_in_kernel_tree() -> tuple[str, str]:
    return _CLOCK_IN_KERNEL_SRC, "protocol_tpu/ops/_fixture_clock_in_kernel.py"


_PLAN_MUTATION_SRC = '''\
import jax


def make_step(plan, fingerprint):
    @jax.jit
    def step(t, inserts, deletes):
        # Delta application belongs in the host stage, pre-dispatch;
        # under a trace it runs once at trace time and the kernel then
        # serves a stale layout forever after.
        new_plan = plan.apply_delta(inserts, deletes, fingerprint=fingerprint)  # VIOLATION: plan-mutation-in-converge
        return t * 2.0, new_plan

    return step
'''


def _plan_mutation_in_converge() -> tuple[str, str]:
    return _PLAN_MUTATION_SRC, "protocol_tpu/trust/_fixture_plan_mutation.py"


_JOURNAL_IN_JIT_SRC = '''\
import jax

from protocol_tpu.obs.journal import JOURNAL


@jax.jit
def step(t):
    # Under a trace this records ONE event at trace time and never
    # again — the flight recorder would replay a stale line forever.
    JOURNAL.record("iteration", residual=t)  # VIOLATION: journal-write-in-jit
    return t * 2.0
'''


def _journal_write_in_jit() -> tuple[str, str]:
    return _JOURNAL_IN_JIT_SRC, "protocol_tpu/trust/_fixture_journal_in_jit.py"


_BLOCKING_INGEST_SRC = '''\
import queue

PENDING = queue.Queue(maxsize=4)


def device_stage(manager, atts, prepared):
    # The epoch loop verifying signatures re-couples convergence
    # cadence to ingest load — admission belongs in the ingest plane.
    results = manager.add_attestations_bulk(atts)  # VIOLATION: blocking-ingest-in-epoch-loop
    # An unbounded blocking put can park the epoch loop forever when
    # the consumer stalls; put_nowait (coalescing) or timeout= are the
    # sanctioned shapes.
    PENDING.put(prepared)
    return results
'''


def _blocking_ingest_in_epoch_loop() -> tuple[str, str]:
    # The fake path lands on an epoch-loop file so the file-scoped
    # pass-6 rule applies exactly as it would to the real module.
    return _BLOCKING_INGEST_SRC, "protocol_tpu/node/pipeline.py"


#: Pass-7 seeded violations (whole-program concurrency rules).  Each
#: source is a self-contained "program": it declares its own thread
#: roots, so the analyzer's reachability machinery runs exactly as it
#: does on the real tree.  Paths land outside the thread-confined
#: trees so the shared-state rules apply.
_UNGUARDED_SHARED_ATTR_SRC = '''\
import threading


class Tally:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0

    def bump(self):
        with self._lock:
            self.count += 1

    def read(self):
        return self.count  # VIOLATION: unguarded-shared-attr


def run():
    t = Tally()
    threading.Thread(target=t.bump).start()
    threading.Thread(target=t.read).start()
'''


def _unguarded_shared_attr() -> tuple[str, str]:
    return _UNGUARDED_SHARED_ATTR_SRC, "protocol_tpu/node/_fixture_shared_attr.py"


_UNGUARDED_RMW_SRC = '''\
import threading


class Hits:
    def __init__(self):
        self.n = 0

    def work(self):
        self.n += 1  # VIOLATION: unguarded-rmw


def run():
    h = Hits()
    threading.Thread(target=h.work, name="w1").start()
    threading.Thread(target=h.work, name="w2").start()
'''


def _unguarded_rmw() -> tuple[str, str]:
    return _UNGUARDED_RMW_SRC, "protocol_tpu/obs/_fixture_rmw.py"


_CHECK_THEN_ACT_SRC = '''\
import threading


class Once:
    def __init__(self):
        self.started = False

    def boot(self):
        if not self.started:
            self.started = True  # VIOLATION: check-then-act


def run():
    o = Once()
    threading.Thread(target=o.boot, name="a").start()
    threading.Thread(target=o.boot, name="b").start()
'''


def _check_then_act() -> tuple[str, str]:
    return _CHECK_THEN_ACT_SRC, "protocol_tpu/ingest/_fixture_check_act.py"


_LOCK_ORDER_CYCLE_SRC = '''\
import threading


class Transfer:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def ab(self):
        with self._a:
            with self._b:  # VIOLATION: lock-order-cycle
                pass

    def ba(self):
        with self._b:
            with self._a:
                pass
'''


def _lock_order_cycle() -> tuple[str, str]:
    return _LOCK_ORDER_CYCLE_SRC, "protocol_tpu/node/_fixture_lock_order.py"


_BLOCKING_UNDER_LOCK_SRC = '''\
import queue
import threading


class Stage:
    def __init__(self):
        self._lock = threading.Lock()
        self._queue = queue.Queue(maxsize=4)

    def push(self, item):
        with self._lock:
            self._queue.put(item)  # VIOLATION: blocking-call-under-lock
'''


def _blocking_call_under_lock() -> tuple[str, str]:
    return _BLOCKING_UNDER_LOCK_SRC, "protocol_tpu/ingest/_fixture_block_lock.py"


_NATIVE_UNDER_LOCK_SRC = '''\
import threading

from protocol_tpu.crypto import native as cnative


class Verifier:
    def __init__(self):
        self._lock = threading.Lock()

    def check(self, sigs):
        with self._lock:
            return cnative.eddsa_verify_batch(sigs)  # VIOLATION: native-call-under-lock
'''


def _native_call_under_lock() -> tuple[str, str]:
    return _NATIVE_UNDER_LOCK_SRC, "protocol_tpu/node/_fixture_native_lock.py"


FIXTURES: dict[str, Fixture] = {
    f.name: f
    for f in (
        Fixture("extra-gather", "gather-budget", _extra_gather, "extra-gather"),
        Fixture("f64-leak", "f64-dtype", _f64_leak, "f64-leak"),
        Fixture(
            "callback-in-jit", "callback-in-jit", _callback_in_jit, "callback-in-jit"
        ),
        Fixture(
            "unsorted-boundary",
            "boundary-sorted",
            _unsorted_boundary,
            "unsorted-boundary",
        ),
        Fixture(
            "scatter-in-step", "scatter-budget", _scatter_in_step, "scatter-in-step"
        ),
        Fixture(
            "missing-donation", "donation-not-materialized", _missing_donation, None
        ),
        Fixture(
            "time-in-jit", "host-clock-in-jit", _time_in_jit, "time-in-jit",
            kind="ast",
        ),
        Fixture(
            "logging-in-jit", "logging-in-jit", _logging_in_jit,
            "logging-in-jit", kind="ast",
        ),
        Fixture(
            "clock-in-kernel-tree", "clock-in-kernel-tree",
            _clock_in_kernel_tree, "clock-in-kernel-tree", kind="ast",
        ),
        Fixture(
            "plan-mutation-in-converge", "plan-mutation-in-converge",
            _plan_mutation_in_converge, "plan-mutation-in-converge",
            kind="ast",
        ),
        Fixture(
            "journal-write-in-jit", "journal-write-in-jit",
            _journal_write_in_jit, "journal-write-in-jit",
            kind="ast",
        ),
        Fixture(
            "blocking-ingest-in-epoch-loop", "blocking-ingest-in-epoch-loop",
            _blocking_ingest_in_epoch_loop, "blocking-ingest-in-epoch-loop",
            kind="ast",
        ),
        Fixture(
            "unguarded-shared-attr", "unguarded-shared-attr",
            _unguarded_shared_attr, "unguarded-shared-attr",
            kind="concurrency",
        ),
        Fixture(
            "unguarded-rmw", "unguarded-rmw", _unguarded_rmw,
            "unguarded-rmw", kind="concurrency",
        ),
        Fixture(
            "check-then-act", "check-then-act", _check_then_act,
            "check-then-act", kind="concurrency",
        ),
        Fixture(
            "lock-order-cycle", "lock-order-cycle", _lock_order_cycle,
            "lock-order-cycle", kind="concurrency",
        ),
        Fixture(
            "blocking-call-under-lock", "blocking-call-under-lock",
            _blocking_call_under_lock, "blocking-call-under-lock",
            kind="concurrency",
        ),
        Fixture(
            "native-call-under-lock", "native-call-under-lock",
            _native_call_under_lock, "native-call-under-lock",
            kind="concurrency",
        ),
    )
}


def run_fixture(name: str) -> list[Finding]:
    """Trace and check one seeded violation; raises KeyError on an
    unknown name (the CLI lists valid ones)."""
    fixture = FIXTURES[name]
    if fixture.kind == "ast":
        from .ast_rules import scan_source

        source, rel_path = fixture.build()
        return scan_source(source, rel_path)
    if fixture.kind == "concurrency":
        from .concurrency import analyze_sources

        source, rel_path = fixture.build()
        return analyze_sources({rel_path: source})
    budget, case = fixture.build()
    return check_case(budget, case)


__all__ = ["FIXTURES", "Fixture", "run_fixture"]
