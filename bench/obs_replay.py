"""Fleet-observability replay: end-to-end freshness under real load.

The ISSUE 11 acceptance run: the churned 200k/2M epoch replay (the
PROVER_r01 shape — EpochPipeline + async ProvingPlane, real PLONK
proofs) with a lineage-sampled attestation stream flowing through the
real admission plane the whole time.  Measures the question the fleet
plane exists to answer:

- ``freshness_p99_ms`` — attestation accepted at the plane → its
  effect in a *proven, servable* score (the including epoch's SNARK
  landed), via the per-stage ``eigentrust_freshness_seconds``
  histograms the lineage tracker feeds;
- ``obs_overhead_pct`` — the measured cost of the lineage + SLO
  instrumentation, expressed against the steady-state epoch seconds:
  micro-benchmarked per-hop costs × the production ingest rate
  (INGEST_r01's accepted sigs/s at the default 1-in-32 sampling) plus
  one SLO evaluation per tick.  The acceptance bar is <1%% of the
  6.1 s steady-state epoch;
- the standing SLO objectives, which must all be green at the end of
  the run (the same engine the node serves at ``GET /slo``).

Writes a perf-sentinel-shaped report (``entries`` with exact metric
strings); record rounds as ``OBS_r<N>.json`` in the repo root.

Run (recorded round)::

    JAX_PLATFORMS=cpu python bench/obs_replay.py \
        --peers 200000 --edges 2000000 --epochs 5 --out OBS_r01.json

``--smoke`` is the CI shape (small graph, commitment prover, seconds).
"""

from __future__ import annotations

import argparse
import json
import statistics
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

#: Default sampling period a production node runs with
#: (ProtocolConfig.lineage_sample_every) — the overhead projection uses
#: it; the replay itself samples 1:1 so every posted attestation is
#: measured.
PRODUCTION_SAMPLE_EVERY = 32
#: INGEST_r01's single-process accepted sigs/s — the production ingest
#: rate the overhead projection scales by.
PRODUCTION_ACCEPTED_PER_S = 1749.0


def _fresh_attestations(epoch_index: int):
    """Five fresh (unique-digest, conserving) signed attestations from
    the fixed set — the per-epoch lineage stream.  Unique score rows
    keep the plane's dedup from eating the re-submissions."""
    from protocol_tpu.crypto import calculate_message_hash
    from protocol_tpu.crypto.eddsa import sign
    from protocol_tpu.node.attestation import Attestation
    from protocol_tpu.node.bootstrap import FIXED_SET, keyset_from_raw

    sks, pks = keyset_from_raw(FIXED_SET)
    atts = []
    for sender in range(len(pks)):
        i = epoch_index * len(pks) + sender
        d1, d2 = i % 200, (i // 200) % 200
        row = [200 + d1 - d2, 200 - d1, 200 + d2, 200, 200]
        _, msgs = calculate_message_hash(pks, [row])
        sig = sign(sks[sender], pks[sender], msgs[0])
        atts.append(
            Attestation(
                sig=sig, pk=pks[sender], neighbours=list(pks), scores=row
            )
        )
    return atts


def _micro_costs() -> dict[str, float]:
    """Measured per-operation costs of the lineage/SLO hot paths."""
    from protocol_tpu.obs.lineage import LineageTracker
    from protocol_tpu.obs.slo import SLOEngine, default_objectives

    t = LineageTracker(sample_every=1, max_entries=1 << 16)
    n = 5000
    t0 = time.perf_counter()
    lids = [t.maybe_begin() for _ in range(n)]
    begin_s = (time.perf_counter() - t0) / n
    t0 = time.perf_counter()
    for lid in lids:
        t.mark(lid, "admitted")
    mark_s = (time.perf_counter() - t0) / n
    t.reset()
    unsampled = LineageTracker(sample_every=0)
    t0 = time.perf_counter()
    for _ in range(n):
        unsampled.maybe_begin()
    unsampled_s = (time.perf_counter() - t0) / n
    engine = SLOEngine()
    for obj in default_objectives(epoch_interval_s=10):
        engine.register(obj)
    t0 = time.perf_counter()
    for _ in range(50):
        engine.evaluate()
    eval_s = (time.perf_counter() - t0) / 50
    return {
        "lineage_begin_us": begin_s * 1e6,
        "lineage_mark_us": mark_s * 1e6,
        "lineage_unsampled_us": unsampled_s * 1e6,
        "slo_evaluate_us": eval_s * 1e6,
    }


def _percentile(values: list[float], q: float) -> float:
    if not values:
        return 0.0
    vals = sorted(values)
    idx = min(int(round(q * (len(vals) - 1))), len(vals) - 1)
    return vals[idx]


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--peers", type=int, default=200_000)
    ap.add_argument("--edges", type=int, default=2_000_000)
    ap.add_argument("--epochs", type=int, default=5)
    ap.add_argument("--churn", type=float, default=0.01)
    ap.add_argument("--workers", type=int, default=1)
    ap.add_argument("--queue-depth", type=int, default=2)
    ap.add_argument("--prover", default="plonk", choices=("plonk", "commitment"))
    ap.add_argument(
        "--interval",
        default="auto",
        help="epoch cadence seconds ('auto' = the measured sync epoch "
        "estimate, prover_storm's production pacing)",
    )
    ap.add_argument("--smoke", action="store_true", help="CI shape: seconds")
    ap.add_argument("--n", type=int, default=0, help="bench round number")
    ap.add_argument("--out", default="OBS_smoke.json")
    args = ap.parse_args(argv)
    if args.smoke:
        args.peers, args.edges = 20_000, 120_000
        args.epochs = min(args.epochs, 3)
        args.prover = "commitment"

    from protocol_tpu.ingest import IngestPlane, IngestPlaneConfig
    from protocol_tpu.models.graphs import scale_free
    from protocol_tpu.node.epoch import Epoch
    from protocol_tpu.node.pipeline import EpochPipeline
    from protocol_tpu.obs.lineage import LINEAGE
    from protocol_tpu.obs.metrics import FRESHNESS_SECONDS
    from protocol_tpu.obs.slo import SLO_ENGINE, install_defaults
    from protocol_tpu.obs.timeline import TIMELINE
    from protocol_tpu.prover import ProvingPlane, ProvingPlaneConfig
    from protocol_tpu.prover.jobs import prove_job
    from tools.prover_pipe import _make_manager

    shape = f"{args.peers // 1000}k/{args.edges // 1_000_000}M"
    micro = _micro_costs()
    print(
        f"obs_replay: micro costs — begin {micro['lineage_begin_us']:.1f}us, "
        f"mark {micro['lineage_mark_us']:.1f}us, unsampled "
        f"{micro['lineage_unsampled_us']:.2f}us, slo eval "
        f"{micro['slo_evaluate_us']:.0f}us"
    )

    manager = _make_manager(
        scale_free(args.peers, args.edges, seed=7), args.prover
    )
    manager.generate_initial_attestations()
    manager.warm_prover()
    cfg = manager.config
    params = (cfg.num_neighbours, cfg.num_iter, cfg.initial_score, cfg.scale)

    # Lineage: sample every accepted attestation of the replay stream.
    LINEAGE.configure(1)
    LINEAGE.reset()

    # -- sync baseline (one epoch + one inline prove, compile eaten) ---
    prepared = manager.prepare_epoch(Epoch(0))
    manager.converge_prepared(prepared, alpha=0.1, max_iter=80)  # compile
    manager.churn(args.churn)
    prepared = manager.prepare_epoch(Epoch(1))
    t0 = time.perf_counter()
    manager.converge_prepared(prepared, alpha=0.1, max_iter=80)
    converge_seconds = time.perf_counter() - t0
    t0 = time.perf_counter()
    prove_job(manager.build_proof_job(Epoch(1)))
    inline_prove_seconds = time.perf_counter() - t0
    sync_epoch_seconds = converge_seconds + inline_prove_seconds
    interval = (
        sync_epoch_seconds if args.interval == "auto" else float(args.interval)
    )
    install_defaults(
        epoch_interval_s=interval,
        freshness_p99_s=max(120.0, 4.0 * interval),
        proof_lag_p99_s=max(60.0, 3.0 * interval),
    )

    # -- the measured run: churned epochs + async proving + ingest -----
    ingest = IngestPlane(manager, IngestPlaneConfig(workers=0)).start()
    plane = ProvingPlane(
        ProvingPlaneConfig(workers=args.workers, queue_depth=args.queue_depth),
        on_proved=lambda r: manager.install_proof(r.epoch, r.pub_ins, r.proof),
    ).start()
    plane.prewarm(params, cfg.prover, cfg.srs_path)

    from protocol_tpu.obs import TRACER

    def device_stage(prepared):
        with TRACER.epoch(prepared.epoch.number):
            result = manager.converge_prepared(prepared, alpha=0.1, max_iter=80)
            plane.submit(manager.build_proof_job(prepared.epoch))
        SLO_ENGINE.evaluate()
        return result

    ticks = []
    run_t0 = time.perf_counter()
    with EpochPipeline(manager, device_stage=device_stage) as pipe:
        for k in range(2, 2 + args.epochs):
            # The lineage stream: fresh signed attestations through the
            # real admission plane, accepted (and lineage-stamped)
            # BEFORE this epoch's graph assembly absorbs them.
            for att in _fresh_attestations(k):
                ingest.submit(att)
            assert ingest.drain(timeout=60), "ingest did not drain"
            manager.churn(args.churn)
            t0 = time.perf_counter()
            pipe.submit(Epoch(k))
            assert pipe.drain(timeout=900), f"epoch {k} did not finish"
            outcome = pipe.outcomes[k]
            assert outcome.error is None, f"epoch {k}: {outcome.error!r}"
            tick = time.perf_counter() - t0
            ticks.append(tick)
            if interval > 0 and tick < interval and k < 1 + args.epochs:
                time.sleep(interval - tick)
    assert plane.drain(timeout=1800), "proving plane did not drain"
    run_seconds = time.perf_counter() - run_t0
    stats = plane.stats()
    slo = SLO_ENGINE.evaluate()
    plane.close()
    ingest.close()

    steady = statistics.median(ticks)

    # -- freshness: the headline numbers -------------------------------
    landed = FRESHNESS_SECONDS.count(stage="proof_landed")
    expected = args.epochs * 5
    assert landed >= expected * 0.6, (
        f"only {landed}/{expected} lineage entries completed end-to-end"
    )
    p99_s = FRESHNESS_SECONDS.quantile(0.99, stage="proof_landed") or 0.0
    p50_s = FRESHNESS_SECONDS.quantile(0.50, stage="proof_landed") or 0.0
    per_epoch_fresh = []
    for k in range(2, 2 + args.epochs):
        rec = TIMELINE.get(k) or {}
        per_epoch_fresh.append(
            {
                "epoch": k,
                "tick_seconds": round(ticks[k - 2], 4),
                "freshness": rec.get("freshness"),
                "proof": (rec.get("proof") or {}).get("state"),
            }
        )

    # -- overhead accounting (<1% of the steady epoch) -----------------
    # Projection at production shape: INGEST_r01's accepted rate, the
    # default 1-in-32 sampling, ~6 hops per sampled entry, plus one SLO
    # evaluation per tick.  All terms are the micro-measured costs
    # above — deterministic accounting, not run-to-run noise.
    per_epoch_atts = PRODUCTION_ACCEPTED_PER_S * interval
    sampled = per_epoch_atts / PRODUCTION_SAMPLE_EVERY
    overhead_s = (
        per_epoch_atts * micro["lineage_unsampled_us"] / 1e6
        + sampled * (micro["lineage_begin_us"] + 6 * micro["lineage_mark_us"]) / 1e6
        + micro["slo_evaluate_us"] / 1e6
    )
    overhead_pct = 100.0 * overhead_s / max(steady, 1e-9)
    assert overhead_pct < 1.0, (
        f"lineage+SLO overhead {overhead_pct:.3f}% of the {steady:.2f}s "
        "steady epoch exceeds the 1% acceptance bar"
    )

    # Every standing objective green at the end of the run.
    violating = sorted(
        k for k, o in slo["objectives"].items() if not o["ok"]
    )
    assert not violating, f"SLO objectives violating after replay: {violating}"

    report = {
        "config": {
            "peers": args.peers,
            "edges": args.edges,
            "epochs": args.epochs,
            "churn": args.churn,
            "workers": args.workers,
            "prover": args.prover,
            "interval_seconds": round(interval, 4),
            "smoke": bool(args.smoke),
            "sample_every": 1,
        },
        "n": args.n or None,
        "sync_epoch_seconds": round(sync_epoch_seconds, 4),
        "run_seconds": round(run_seconds, 4),
        "micro_costs_us": {k: round(v, 3) for k, v in micro.items()},
        "proofs": {
            "completed": stats["completed"],
            "superseded": stats["superseded"],
            "failed": stats["failed"],
        },
        "lineage_completed": landed,
        "per_epoch": per_epoch_fresh,
        "slo": slo,
        "entries": [
            {
                "metric": (
                    f"end-to-end freshness accepted->proven "
                    f"({shape} churned, {args.prover}, async plane)"
                ),
                "value": round(p99_s * 1000.0, 1),
                "unit": "ms p99 accepted-to-proven",
                "freshness_p99_ms": round(p99_s * 1000.0, 1),
                "freshness_p50_ms": round(p50_s * 1000.0, 1),
                "completed": landed,
                "steady_state_epoch_seconds": round(steady, 4),
            },
            {
                "metric": (
                    f"lineage+SLO overhead vs steady epoch ({shape}, "
                    f"1:{PRODUCTION_SAMPLE_EVERY} sampling at "
                    f"{PRODUCTION_ACCEPTED_PER_S:.0f} sigs/s)"
                ),
                "value": round(overhead_pct, 4),
                "unit": "percent of steady-state epoch",
                "obs_overhead_pct": round(overhead_pct, 4),
                "overhead_seconds_per_epoch": round(overhead_s, 6),
            },
        ],
    }
    Path(args.out).write_text(json.dumps(report, indent=2) + "\n")
    LINEAGE.configure(0)
    LINEAGE.reset()
    print(
        f"obs_replay: freshness p50 {p50_s:.2f}s / p99 {p99_s:.2f}s "
        f"({landed} completions), steady epoch {steady:.2f}s, "
        f"obs overhead {overhead_pct:.3f}% (<1% bar), SLOs green; "
        f"report at {args.out}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
