"""Measure the fused pipeline's boundary-read patterns on the real
chip (PERF.md §8) — run when the TPU tunnel is up.

PERF.md §7 left one open variable: whether gathers over *graph-static*
indices (host-precomputed, loop-invariant) run at §1's op-bound ~7.2
cycles/element like data-dependent random gathers, or stream.  This
probe measures, at the bench graph's boundary shape (S = 14.7M runs
over an L = 50.5M-slot prefix array):

1. the v1 bridge pattern — 4 separate 1-wide gathers at dst-sorted
   (random-order) run boundaries (hi/lo lanes at start−1 and end);
2. the v2 bridge pattern — one 2-wide slice gather at bucket-order
   (strictly increasing) run ends with ``indices_are_sorted=True``,
   adjacent differencing (a shift, no gather), then the single
   n_segments dst permutation — the only random pass;
3. isolation probes: the sorted 2-wide gather alone, the random
   permutation alone, and a data-dependent-index control (same index
   values, but derived from the loop carry so XLA cannot treat them as
   loop-invariant).

Timing-loop doctrine (PERF.md §1): every measured op carries a data
dependence on the loop state through its *operand* (``+ acc * eps``) so
WhileLoopInvariantCodeMotion can't hoist it; the indices stay
loop-invariant — that is exactly the graph-static pattern under test —
except in the control, which threads the carry through the index array
via a select.  The operand dep-chain add is a full-array elementwise
pass (~0.5 ms at the v5e's HBM bandwidth), so every number is a slight
over-estimate — an upper bound, like the rest of PERF.md.
"""

import pathlib
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))
import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

S = 14_700_000  # bench-graph n_segments (PERF.md §7)
L = 49_344 * 1024  # bench-graph slot count (n_rows * ROW)
REPS = 8
eps = jnp.float32(1e-38)

rng = np.random.default_rng(0)
hi = rng.random(L, np.float32)
lo = rng.random(L, np.float32) * 1e-7
# Bucket-order run ends: strictly increasing slots (v2 layout).
ends_sorted = np.sort(rng.choice(L, S, replace=False)).astype(np.int32)
first = np.zeros(S, bool)
first[0] = True
first[1:] = (ends_sorted[1:] >> 10) != (ends_sorted[:-1] >> 10)
# dst permutation of the partials (v2) / dst-sorted boundaries (v1).
perm = rng.permutation(S).astype(np.int32)
starts_v1 = np.maximum(ends_sorted - 3, 0)[perm]
ends_v1 = ends_sorted[perm]

hi_d = jax.device_put(jnp.asarray(hi))
lo_d = jax.device_put(jnp.asarray(lo))
cum2_d = jax.device_put(jnp.stack([jnp.asarray(hi), jnp.asarray(lo)], axis=-1))
ends_d = jax.device_put(jnp.asarray(ends_sorted))
first_d = jax.device_put(jnp.asarray(first))
perm_d = jax.device_put(jnp.asarray(perm))
starts_v1_d = jax.device_put(jnp.asarray(starts_v1))
ends_v1_d = jax.device_put(jnp.asarray(ends_v1))


@jax.jit
def chain_v1(hi, lo, starts, ends):
    """4 × 1-wide static-index random gathers (the pre-§8 bridge)."""

    def step(_, acc):
        h, l = hi + acc * eps, lo + acc * eps
        partial = (h[ends] - h[starts]) + (l[ends] - l[starts])
        return partial[0]

    return lax.fori_loop(0, REPS, step, jnp.float32(0))


@jax.jit
def chain_v2(cum2, ends, first, perm):
    """1 × 2-wide sorted gather + shift + 1 × random permutation."""

    def step(_, acc):
        e = (cum2 + acc * eps).at[ends].get(
            indices_are_sorted=True, unique_indices=True
        )
        eh, el = e[:, 0], e[:, 1]
        zero = jnp.zeros(1, eh.dtype)
        ph = jnp.where(first, 0.0, jnp.concatenate([zero, eh[:-1]]))
        pl = jnp.where(first, 0.0, jnp.concatenate([zero, el[:-1]]))
        partial = (eh - ph) + (el - pl)
        return partial[perm][0]

    return lax.fori_loop(0, REPS, step, jnp.float32(0))


@jax.jit
def chain_sorted_only(cum2, ends):
    def step(_, acc):
        e = (cum2 + acc * eps).at[ends].get(
            indices_are_sorted=True, unique_indices=True
        )
        return e[0, 0]

    return lax.fori_loop(0, REPS, step, jnp.float32(0))


@jax.jit
def chain_random_only(hi, ends):
    def step(_, acc):
        return (hi + acc * eps)[ends][0]

    return lax.fori_loop(0, REPS, step, jnp.float32(0))


@jax.jit
def chain_data_dependent(hi, ends):
    """Control: identical index values, but the index array is derived
    from the loop carry (a select XLA cannot fold), so the compiler
    must treat them as data-dependent every iteration."""

    def step(_, acc):
        idx = jnp.where(acc > -1.0, ends, ends[::-1])
        return (hi + acc * eps)[idx][0]

    return lax.fori_loop(0, REPS, step, jnp.float32(0))


for name, fn, args in [
    ("v1 bridge: 4x 1-wide random static-idx", chain_v1,
     (hi_d, lo_d, starts_v1_d, ends_v1_d)),
    ("v2 bridge: 2-wide sorted + 1 permutation", chain_v2,
     (cum2_d, ends_d, first_d, perm_d)),
    ("sorted 2-wide gather alone", chain_sorted_only, (cum2_d, ends_d)),
    ("random 1-wide gather alone (static idx)", chain_random_only,
     (hi_d, ends_v1_d)),
    ("random 1-wide gather alone (data-dep idx)", chain_data_dependent,
     (hi_d, ends_v1_d)),
]:
    r = np.asarray(fn(*args))  # compile + warm up
    t0 = time.perf_counter()
    for _ in range(2):
        r = np.asarray(fn(*args))
    dt = (time.perf_counter() - t0) / 2 / REPS
    print(f"{name}: {dt * 1e3:.1f} ms per {S / 1e6:.1f}M-boundary pass", flush=True)
