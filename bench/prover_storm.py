"""Proving-plane load bench: epochs vs SNARKs under sustained churn.

Measures the ISSUE 10 headline on one machine: steady-state epoch
wall-clock with the SNARK **on** the tick (sequential
converge+prove) vs **off** it (async proving plane, prove overlapped),
plus the plane's sustained throughput and tail behavior —

- ``steady_state_epoch_seconds`` (async) vs
  ``sync_epoch_seconds`` (the PR 5-shaped tick with the prove
  serialized back in): the overlap headline,
- ``proofs_per_epoch`` sustained and the terminal-state census
  (proved / superseded / failed — every epoch explicit, none silent),
- ``p99_proof_lag_ms`` (submit → proved wall per job),
- an optional crash mix (``--chaos N``): N jobs carry a crash-once
  marker, exercising the worker-kill → pool rebuild → retry → proved
  path under load.

Writes a perf-sentinel-shaped report (``entries`` list with exact
metric strings) — record rounds as ``PROVER_r<N>.json`` in the repo
root; ``tools/perf_sentinel.py`` tracks the series.

Run (recorded round)::

    JAX_PLATFORMS=cpu python bench/prover_storm.py \
        --peers 200000 --edges 2000000 --epochs 5 --out PROVER_r01.json

``--smoke`` is the CI shape (small graph, commitment prover, seconds
not minutes).
"""

from __future__ import annotations

import argparse
import json
import statistics
import sys
import tempfile
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np


def _percentile(values: list[float], q: float) -> float:
    if not values:
        return 0.0
    vals = sorted(values)
    idx = min(int(round(q * (len(vals) - 1))), len(vals) - 1)
    return vals[idx]


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--peers", type=int, default=200_000)
    ap.add_argument("--edges", type=int, default=2_000_000)
    ap.add_argument("--epochs", type=int, default=5)
    ap.add_argument("--churn", type=float, default=0.01)
    ap.add_argument("--workers", type=int, default=1)
    ap.add_argument("--queue-depth", type=int, default=2)
    ap.add_argument(
        "--prover", default="plonk", choices=("plonk", "commitment")
    )
    ap.add_argument(
        "--zk-backend",
        default="native",
        choices=("native", "graft"),
        help="proving-kernel backend stamped on every ProofJob "
        "(ISSUE 20): proofs are byte-identical either way, the knob "
        "moves where the MSM/NTT seconds are spent",
    )
    ap.add_argument(
        "--chaos",
        type=int,
        default=0,
        help="jobs carrying a crash-once marker (worker killed mid-"
        "prove, pool rebuilt, job retried)",
    )
    ap.add_argument(
        "--interval",
        default="auto",
        help="epoch cadence in seconds (the node's epoch_interval): "
        "ticks fire this far apart, like production — 'auto' paces at "
        "the measured sync epoch duration (the best cadence a "
        "prove-on-tick node could sustain), 0 drives ticks "
        "back-to-back (saturation: on a 1-core host converge then "
        "time-slices against in-flight proves and the tick number "
        "absorbs the contention)",
    )
    ap.add_argument("--smoke", action="store_true", help="CI shape: seconds, not minutes")
    ap.add_argument("--n", type=int, default=0, help="bench round number")
    ap.add_argument("--out", default="PROVER_smoke.json")
    args = ap.parse_args(argv)
    if args.smoke:
        args.peers, args.edges = 20_000, 120_000
        args.epochs = min(args.epochs, 3)
        args.prover = "commitment"

    from protocol_tpu.models.graphs import scale_free
    from protocol_tpu.node.epoch import Epoch
    from protocol_tpu.node.pipeline import EpochPipeline
    from protocol_tpu.obs.metrics import PROVER_WORKER_RESTARTS
    from protocol_tpu.prover import ProvingPlane, ProvingPlaneConfig, crash_once_marker
    from tools.prover_pipe import _make_manager

    shape = f"{args.peers // 1000}k/{args.edges // 1_000_000}M"
    # The zk backend rides the metric string only when it departs from
    # the default, so the native series stays continuous across rounds
    # recorded before the knob existed.
    if args.zk_backend != "native":
        shape = f"{shape}, zk={args.zk_backend}"
    manager = _make_manager(
        scale_free(args.peers, args.edges, seed=7),
        args.prover,
        args.zk_backend,
    )
    manager.generate_initial_attestations()
    manager.warm_prover()
    cfg = manager.config
    params = (cfg.num_neighbours, cfg.num_iter, cfg.initial_score, cfg.scale)

    # -- baseline: the SNARK serialized back into the tick -------------
    # One epoch of converge (compile eaten by a throwaway) plus one
    # in-process prove = the sequential tick this plane removes.
    from protocol_tpu.prover.jobs import prove_job

    prepared = manager.prepare_epoch(Epoch(0))
    manager.converge_prepared(prepared, alpha=0.1, max_iter=80)  # compile
    manager.churn(args.churn)
    prepared = manager.prepare_epoch(Epoch(1))
    t0 = time.perf_counter()
    manager.converge_prepared(prepared, alpha=0.1, max_iter=80)
    converge_seconds = time.perf_counter() - t0
    t0 = time.perf_counter()
    prove_job(manager.build_proof_job(Epoch(1)))
    inline_prove_seconds = time.perf_counter() - t0
    sync_epoch_seconds = converge_seconds + inline_prove_seconds

    # -- the async run -------------------------------------------------
    restarts0 = PROVER_WORKER_RESTARTS.value()
    plane = ProvingPlane(
        ProvingPlaneConfig(workers=args.workers, queue_depth=args.queue_depth),
        on_proved=lambda r: manager.install_proof(r.epoch, r.pub_ins, r.proof),
    ).start()
    plane.prewarm(params, cfg.prover, cfg.srs_path)
    chaos_left = args.chaos
    chaos_dir = tempfile.mkdtemp(prefix="prover_storm_chaos_")

    def device_stage(prepared):
        nonlocal chaos_left
        # Tick-end enqueue (the node's async shape): converge first,
        # then hand the SNARK to the plane so the prove burns the
        # inter-tick gap, not this tick's core budget.
        result = manager.converge_prepared(prepared, alpha=0.1, max_iter=80)
        job = manager.build_proof_job(prepared.epoch)
        if chaos_left > 0:
            chaos_left -= 1
            import dataclasses

            job = dataclasses.replace(
                job,
                chaos=crash_once_marker(
                    f"{chaos_dir}/epoch_{prepared.epoch.number}.flag"
                ),
            )
        plane.submit(job)
        return result

    interval = (
        sync_epoch_seconds if args.interval == "auto" else float(args.interval)
    )
    ticks = []
    run_t0 = time.perf_counter()
    with EpochPipeline(manager, device_stage=device_stage) as pipe:
        for k in range(2, 2 + args.epochs):
            manager.churn(args.churn)
            t0 = time.perf_counter()
            pipe.submit(Epoch(k))
            assert pipe.drain(timeout=900), f"epoch {k} did not finish"
            outcome = pipe.outcomes[k]
            assert outcome.error is None, f"epoch {k}: {outcome.error!r}"
            tick = time.perf_counter() - t0
            ticks.append(tick)
            # Production cadence: the next boundary fires `interval`
            # after this one (Skip semantics) — the gap is where the
            # in-flight prove gets the core(s).
            if interval > 0 and tick < interval and k < 1 + args.epochs:
                time.sleep(interval - tick)
    assert plane.drain(timeout=1800), "proving plane did not drain"
    run_seconds = time.perf_counter() - run_t0
    stats = plane.stats()
    plane.close()

    steady = statistics.median(ticks)
    lags_ms = [
        1000.0 * s["lag_seconds"]
        for s in stats["states"].values()
        if s["state"] == "proved" and s.get("lag_seconds") is not None
    ]
    proves = [
        s["prove_seconds"]
        for s in stats["states"].values()
        if s.get("prove_seconds") is not None
    ]
    # Every storm epoch must terminate explicitly; the newest proves.
    for k in range(2, 2 + args.epochs):
        state = stats["states"].get(k, {}).get("state")
        assert state in ("proved", "superseded"), (k, state)
    assert stats["states"][1 + args.epochs]["state"] == "proved"
    assert stats["failed"] == 0, stats
    if args.chaos:
        assert PROVER_WORKER_RESTARTS.value() - restarts0 >= 1, (
            "chaos jobs were configured but no worker restart was observed"
        )

    report = {
        "config": {
            "peers": args.peers,
            "edges": args.edges,
            "epochs": args.epochs,
            "churn": args.churn,
            "workers": args.workers,
            "queue_depth": args.queue_depth,
            "prover": args.prover,
            "zk_backend": args.zk_backend,
            "chaos": args.chaos,
            "interval_seconds": round(interval, 4),
            "smoke": bool(args.smoke),
        },
        "n": args.n or None,
        "converge_seconds": round(converge_seconds, 4),
        "inline_prove_seconds": round(inline_prove_seconds, 4),
        "worker_restarts": PROVER_WORKER_RESTARTS.value() - restarts0,
        "proofs": {
            "completed": stats["completed"],
            "superseded": stats["superseded"],
            "failed": stats["failed"],
        },
        "entries": [
            {
                "metric": (
                    f"steady-state epoch wall-clock with async proving "
                    f"plane ({shape}, {args.prover}, "
                    f"{args.workers} workers)"
                ),
                "value": round(steady, 4),
                "unit": "seconds",
                "steady_state_epoch_seconds": round(steady, 4),
                "sync_epoch_seconds": round(sync_epoch_seconds, 4),
                "epoch_reduction_vs_sync": round(
                    1.0 - steady / max(sync_epoch_seconds, 1e-9), 4
                ),
                "per_epoch_seconds": [round(t, 4) for t in ticks],
            },
            {
                "metric": (
                    f"proving-plane proof latency ({shape}, "
                    f"{args.prover}, {args.workers} workers)"
                ),
                "value": round(_percentile(lags_ms, 0.99), 1),
                "unit": "ms p99 submit-to-proved",
                "p99_proof_lag_ms": round(_percentile(lags_ms, 0.99), 1),
                "median_prove_seconds": round(
                    statistics.median(proves), 4
                )
                if proves
                else None,
                "proofs_per_epoch": round(
                    stats["completed"] / max(args.epochs, 1), 3
                ),
                "sustained_proofs_per_s": round(
                    stats["completed"] / max(run_seconds, 1e-9), 4
                ),
            },
        ],
    }
    Path(args.out).write_text(json.dumps(report, indent=2) + "\n")
    e0, e1 = report["entries"]
    print(
        f"prover_storm: steady epoch {e0['value']}s async vs "
        f"{e0['sync_epoch_seconds']}s sync "
        f"({100 * e0['epoch_reduction_vs_sync']:.0f}% off the tick); "
        f"{report['proofs']['completed']} proved / "
        f"{report['proofs']['superseded']} superseded / 0 failed, "
        f"p99 lag {e1['p99_proof_lag_ms']} ms; report at {args.out}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
