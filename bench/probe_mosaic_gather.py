"""Probe the Mosaic dynamic_gather support envelope: axis1 (lane)
range scaling, axis0 (sublane) shapes, transposes, and XLA gather
speed vs table size.

Recorded output (TPU v5 lite via axon tunnel, jax 0.9.0, 2026-07-29;
compile/crash envelope only — the per-op timings of that run were
dispatch-dominated and are superseded):

    axis1 (8192,128) range=128: compiles, correct
    axis1 range 1024/8192/16384/131072/1048576: Mosaic compiler crash
    axis0 (8,128) range=8: compiles, correct
    axis0 range 64/256/1024/8192: Mosaic compiler crash
    transpose (128,8192) and (8192,128): compile, correct
    XLA gather 8M indices: table-size independent (op-bound)

Timing-loop doctrine (PERF.md §1): the original per-dispatch loops here
measured the tunnel's ~70 ms dispatch, not the op.  Every timing below
now runs REPS dependent iterations inside one jit — the measured op's
operand is perturbed by the loop carry (the ``dep_chain`` pattern from
``bench/profile_components.py``) so ``WhileLoopInvariantCodeMotion``
cannot hoist the body, and the op output feeds the carry so nothing is
dead.  pallas_call is opaque to the algebraic simplifier, so a
one-element read of its output keeps the whole kernel live; the XLA
gather is consumed through a full-array ``max`` for the same reason.

Conclusion (PERF.md §1): cross-vreg dynamic gathers are unusable on
this toolchain, which rules out a VMEM-resident-table Pallas gather
for the 1M-entry score table.
"""
import pathlib
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))
import jax, jax.numpy as jnp, numpy as np
from jax import lax
from jax.experimental import pallas as pl

rng = np.random.default_rng(0)
REPS = 8
EPS = jnp.float32(1e-38)


def chain(body):
    """REPS dependent iterations of ``body(perturbation, *args)`` inside
    one jit: the scalar carry perturbs the measured op's operand (LICM
    can't hoist) and is fed from its output (DCE can't drop it)."""

    @jax.jit
    def run(*args):
        def step(_, acc):
            return body(acc * EPS, *args)

        return lax.fori_loop(0, REPS, step, jnp.float32(0))

    return run


def timed_chain(name, body, *args):
    f = chain(body)
    jax.block_until_ready(f(*args))  # compile + warm up
    t0 = time.perf_counter()
    for _ in range(2):
        r = jax.block_until_ready(f(*args))
    dt = (time.perf_counter() - t0) / 2 / REPS
    return r, dt


def bench_gather(axis, R, L):
    rng_hi = R if axis == 0 else L
    name = f"axis{axis} ({R},{L}) range={rng_hi}"
    try:
        t = jax.device_put(jnp.asarray(rng.random((R, L), dtype=np.float32)))
        idx = jax.device_put(jnp.asarray(rng.integers(0, rng_hi, (R, L)).astype(np.int32)))
        kernel = pl.pallas_call(
            lambda t_ref, i_ref, o_ref: o_ref.__setitem__(
                slice(None), jnp.take_along_axis(t_ref[:], i_ref[:], axis=axis)),
            out_shape=jax.ShapeDtypeStruct((R, L), jnp.float32),
        )
        # Correctness once, outside the timing chain.
        r = jax.block_until_ready(jax.jit(kernel)(t, idx))
        tn, ixn = np.asarray(t), np.asarray(idx)
        if axis == 0:
            exp = tn[ixn, np.arange(L)[None, :]]
        else:
            exp = tn[np.arange(R)[:, None], ixn]
        ok = np.array_equal(np.asarray(r), exp)
        _, dt = timed_chain(name, lambda d, t, i: kernel(t + d, i)[0, 0], t, idx)
        print(f"{name}: {dt*1e6:.1f} us  ({R*L/dt/1e9:.1f} Gelem/s)  correct={ok}", flush=True)
    except Exception as e:
        s = str(e).splitlines()
        print(f"{name}: FAILED — {type(e).__name__}: {s[0][:120] if s else ''}", flush=True)


print("== axis1 (lane gather) range scaling ==", flush=True)
for R, L in [(8192, 128), (1024, 1024), (128, 8192), (64, 16384), (8, 131072), (8, 1048576)]:
    bench_gather(1, R, L)

print("== axis0 (sublane gather) shapes ==", flush=True)
for R, L in [(8, 128), (64, 128), (256, 128), (1024, 128), (8192, 128)]:
    bench_gather(0, R, L)

print("== in-kernel transpose ==", flush=True)
for R, L in [(128, 8192), (8192, 128)]:
    try:
        t = jax.device_put(jnp.asarray(rng.random((R, L), dtype=np.float32)))
        kernel = pl.pallas_call(
            lambda t_ref, o_ref: o_ref.__setitem__(slice(None), t_ref[:].T),
            out_shape=jax.ShapeDtypeStruct((L, R), jnp.float32),
        )
        r = jax.block_until_ready(jax.jit(kernel)(t))
        ok = np.array_equal(np.asarray(r), np.asarray(t).T)
        _, dt = timed_chain(f"transpose ({R},{L})", lambda d, t: kernel(t + d)[0, 0], t)
        print(f"transpose ({R},{L}): {dt*1e6:.1f} us  correct={ok}", flush=True)
    except Exception as e:
        s = str(e).splitlines()
        print(f"transpose ({R},{L}): FAILED — {type(e).__name__}: {s[0][:120] if s else ''}", flush=True)

print("== XLA gather vs table size (8M indices) ==", flush=True)
E = 8_000_000
for tbl in [16384, 131072, 1048576]:
    t = jax.device_put(jnp.asarray(rng.random(tbl, dtype=np.float32)))
    idx = jax.device_put(jnp.asarray(rng.integers(0, tbl, E).astype(np.int32)))
    _, dt = timed_chain(f"XLA gather 8M from {tbl}", lambda d, t, i: (t + d)[i].max(), t, idx)
    print(f"XLA gather 8M from {tbl}: {dt*1e3:.2f} ms/pass", flush=True)
