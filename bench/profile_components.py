"""LICM-defeated component breakdown of the headline bench step.

Every loop body depends on the carry so WhileLoopInvariantCodeMotion
cannot hoist the op being measured — without this, XLA hoists any
loop-invariant gather and the "benchmark" times dispatch overhead
(PERF.md §1 documents both the trap and the numbers).

Recorded output (TPU v5 lite via axon tunnel, 2026-07-29):

    gather 50M (dep): 386.08 ms/iter  (3089 ms total)
    w*gather 50M (dep): 385.75 ms/iter  (3086 ms total)
    rowsum_sorted 50M (dep): 65.68 ms/iter  (525 ms total)
    50M elementwise mul (dep): 8.81 ms/iter  (71 ms total)

Conclusion: the bench is gather-op-bound (86 % of the 447 ms step).
"""
import pathlib
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))
import jax, jax.numpy as jnp, numpy as np
from jax import lax
from protocol_tpu.ops.sparse import rowsum_sorted

rng = np.random.default_rng(0)
E, N = 50_000_000, 1_000_000
REPS = 8


def timeit(name, fn, *args, reps=2, per=REPS):
    f = jax.jit(fn)
    r = np.asarray(jax.tree.leaves(f(*args))[0])
    t0 = time.perf_counter()
    for _ in range(reps):
        r = np.asarray(jax.tree.leaves(f(*args))[0])
    dt = (time.perf_counter() - t0) / reps
    print(f"{name}: {dt/per*1e3:.2f} ms/iter  ({dt*1e3:.0f} ms total)", flush=True)


t_full = jax.device_put(jnp.asarray(rng.random(N, dtype=np.float32)))
src = jax.device_put(jnp.asarray(rng.integers(0, N, E).astype(np.int32)))
w = jax.device_put(jnp.asarray(rng.random(E, dtype=np.float32)))
contrib = jax.device_put(jnp.asarray(rng.random(E, dtype=np.float32)))
row_ptr = jax.device_put(jnp.asarray(
    np.searchsorted(np.sort(rng.integers(0, N, E)), np.arange(N + 1)).astype(np.int32)))

EPS = jnp.float32(1e-38)

def dep_chain(body):
    """body(x_perturbed, *args) -> array; carry a scalar that perturbs
    the input each iteration so nothing is loop-invariant."""
    def run(*args):
        def step(_, acc):
            return body(acc * EPS, *args)
        return lax.fori_loop(0, REPS, step, jnp.float32(0))
    return run

timeit("gather 50M (dep)", dep_chain(lambda d, t, s: (t + d)[s].max()), t_full, src)
timeit("w*gather 50M (dep)", dep_chain(lambda d, t, s, w: (w * (t + d)[s]).max()), t_full, src, w)
timeit("rowsum_sorted 50M (dep)", dep_chain(
    lambda d, c, rp: rowsum_sorted(c + d, rp).max()), contrib, row_ptr)
timeit("50M elementwise mul (dep)", dep_chain(lambda d, c, w: ((c + d) * w).max()), contrib, w)
