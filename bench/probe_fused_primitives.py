"""Probe the primitives the fused windowed-SpMV pipeline (PERF.md §7)
is built from, on the real chip:

1. XLA transpose throughput at the pipeline's shapes:
   - big bucket transpose (W, W, S) axes (0,1) — 256 B granularity
   - per-region matrix transposes (R, 64, 1024) <-> (R, 1024, 64) —
     4 B granularity
2. A region-table windowed gather: same 8-way select chain as
   ops/gather_window.py but the VMEM table block is indexed by the
   leading grid dimension (one 256 KB region per step) instead of one
   resident 4 MB table.

Timing-loop doctrine (PERF.md §1): every measured op must carry a data
dependence on the loop state or XLA's WhileLoopInvariantCodeMotion
hoists it and the "benchmark" times dispatch overhead.  For the
transposes a scalar carry is NOT enough — slicing one element of a
transpose lets the algebraic simplifier fold the slice *through* the
transpose and delete the op entirely — so the transpose loops ping-pong
two full-array carries: each body materializes two transposes whose
operands are loop state and whose results become loop state, which
neither LICM nor the simplifier can remove.  Reported numbers divide by
the two transposes per iteration (the chained adds ride along, so these
are slight over-estimates — upper bounds, like the rest of PERF.md).
The Pallas gather keeps the scalar-carry pattern: a pallas_call is
opaque to the simplifier, so feeding the carry through its operand and
consuming one output element suffices.
"""

import pathlib
import sys
import time
from functools import partial

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))
import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

REPS = 8
eps = jnp.float32(1e-38)


def timed(name, fn, *args, per=REPS):
    r = np.asarray(jax.tree.leaves(fn(*args))[0])  # compile + warm up
    t0 = time.perf_counter()
    for _ in range(2):
        r = np.asarray(jax.tree.leaves(fn(*args))[0])
    dt = (time.perf_counter() - t0) / 2 / per
    print(f"{name}: {dt*1e3:.2f} ms/pass", flush=True)
    return dt


def transpose_chain(x, perm):
    """REPS iterations, two dependent full-array transposes each: the
    ping-pong carries make every transpose's operand and result loop
    state, so nothing can be hoisted, folded, or dead-code-eliminated.
    """

    @jax.jit
    def run(x):
        xt = jnp.transpose(x, perm)  # loop-invariant; hoisted, unmeasured

        def step(_, carry):
            a, b = carry  # a: x-shaped, b: transposed-shaped
            b2 = (x + a * eps).transpose(*perm)
            a2 = (xt + b * eps).transpose(*perm)
            return a2, b2

        z = jnp.zeros_like(x)
        a, b = lax.fori_loop(0, REPS, step, (z, jnp.transpose(z, perm)))
        return a[0, 0, 0] + b[0, 0, 0]

    return run


# ---- 1. transposes ----
W, S = 1024, 64
x = jnp.asarray(np.random.default_rng(0).random((W, W, S), np.float32))
y = jnp.asarray(np.random.default_rng(1).random((1024, 64, 1024), np.float32))

# (1, 0, 2) and (0, 2, 1) are involutions, so the ping-pong carries keep
# one static shape.  2 transposes per iteration -> per=2*REPS.
timed("big transpose (1024,1024,64)->(1,0,2) 268MB", transpose_chain(x, (1, 0, 2)), x, per=2 * REPS)
timed("region transpose (1024,64,1024)->(0,2,1) 268MB", transpose_chain(y, (0, 2, 1)), y, per=2 * REPS)

# ---- 2. region-table windowed gather ----
BLOCK_ROWS = 64  # vreg-rows per region: 64 * 1024 slots = one region


def _kernel(wid_ref, t_ref, local_ref, out_ref):
    blk = pl.program_id(0)
    for v in range(BLOCK_ROWS):
        wid = wid_ref[blk * BLOCK_ROWS + v]
        win = t_ref[pl.ds(wid * 8, 8), :]
        lidx = local_ref[pl.ds(v * 8, 8), :]
        sub = lidx // 128
        lane = lidx % 128
        acc = jnp.zeros((8, 128), jnp.float32)
        for k in range(8):
            rowk = jnp.broadcast_to(win[k : k + 1, :], (8, 128))
            g = jnp.take_along_axis(rowk, lane, axis=1)
            acc = jnp.where(sub == k, g, acc)
        out_ref[pl.ds(v * 8, 8), :] = acc


@partial(jax.jit, static_argnames=("n_regions",))
def gather_region(wid, table, local, *, n_regions):
    # table: (n_regions*512, 128) f32; each region's slice is its own
    # (512,128) VMEM block.  local: (n_regions*512, 128) int32 with
    # window-local indices; wid: per vreg-row window id in [0, 64).
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_regions,),
        in_specs=[
            pl.BlockSpec((512, 128), lambda i, wid_ref: (i, 0)),
            pl.BlockSpec((BLOCK_ROWS * 8, 128), lambda i, wid_ref: (i, 0)),
        ],
        out_specs=pl.BlockSpec((BLOCK_ROWS * 8, 128), lambda i, wid_ref: (i, 0)),
    )
    return pl.pallas_call(
        _kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n_regions * 512, 128), jnp.float32),
    )(wid, table, local)


n_regions = 1024
rng = np.random.default_rng(2)
tbl = jnp.asarray(rng.random((n_regions * 512, 128), np.float32))
# Random window-local permutation structure: each row reads within one
# random window of its region.
wid = jnp.asarray(rng.integers(0, 64, n_regions * BLOCK_ROWS).astype(np.int32))
loc = jnp.asarray(rng.integers(0, 1024, (n_regions * 512, 128)).astype(np.int32))


@jax.jit
def chain_region(wid, tbl, loc):
    # The carry perturbs the table operand; the pallas_call is opaque to
    # the simplifier, so consuming one output element keeps the whole
    # kernel live while LICM sees a loop-varying operand.
    def step(_, acc):
        return gather_region(wid, tbl + acc * eps, loc, n_regions=n_regions)[0, 0]

    return lax.fori_loop(0, REPS, step, jnp.float32(0))


timed("region-table windowed gather 67M slots", chain_region, wid, tbl, loc)
