"""MSM/NTT micro-bench across the zk kernel backends.

Measures the proving plane's two inner loops — Pippenger MSM over the
G1 ladder and the radix-2 NTT — per ``zk_backend`` at power-of-two
sizes, reporting ``msm_points_per_s`` and ``ntt_butterflies_per_s``
(butterflies = (n/2)·log2(n) per transform).  Optionally times one
full epoch prove (``--prove``) for the ``prove_seconds`` series.

Timing loops are LICM-proof: every rep draws its scalar vector from a
rotating pool (so no iteration is loop-invariant), results are synced
(``block_until_ready`` on the jit path, the ctypes call is
synchronous) and folded into a checksum that lands in the report — a
compiler or a lazy runtime cannot elide the timed work without
changing the output.

Backends:

- ``native``: the ctypes runtime (sizes up to 2^16 by default);
- ``graft``: the jit multi-limb Pippenger/NTT (sizes capped at 2^12
  by default — one XLA:CPU MSM rep at 2^12 is tens of seconds, and
  the point of the row is the parity-checked lowering the TPU
  projection in PERF.md §22 scales from, not CPU supremacy).

Writes a perf-sentinel-shaped report (``entries`` list with exact
metric strings) — record rounds as ``MSM_r<N>.json`` in the repo
root; ``tools/perf_sentinel.py`` tracks the series.

Run (recorded round)::

    JAX_PLATFORMS=cpu python bench/msm_bench.py --out MSM_r01.json

``--smoke`` is the CI shape (2^10 only, one rep, both backends).
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np


def _setup_jax_cache() -> None:
    """Persist compiled kernels next to the keygen cache (the
    tests/conftest.py doctrine): repeat bench runs must measure the
    kernels, not XLA's compile times."""
    import os
    import pathlib

    import jax

    cache_root = os.environ.setdefault(
        "PROTOCOL_TPU_CACHE",
        str(Path(__file__).resolve().parent.parent / ".cache" / "protocol_tpu"),
    )
    jax_cache = pathlib.Path(cache_root) / "jax"
    jax_cache.mkdir(parents=True, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", str(jax_cache))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)


def _scalar_pool(rng: np.random.Generator, n: int, pool: int, R: int):
    """A rotating pool of scalar vectors (python ints < R)."""
    return [
        [int.from_bytes(rng.bytes(32), "little") % R for _ in range(n)]
        for _ in range(pool)
    ]


def _bench_msm(backend: str, srs, sizes, reps: int, rng, R: int):
    """Per-size MSM timing against the SRS ladder prefix."""
    from protocol_tpu.utils.limbs import to_limbs_fast
    from protocol_tpu.zk import graft as zk_graft
    from protocol_tpu.zk import native as zk_native

    if backend == "graft":
        cache = zk_graft.point_cache(srs.g1_powers)
    else:
        point_limbs = zk_native._points_to_limbs(srs.g1_powers)

    rows = []
    for n in sizes:
        pool = _scalar_pool(rng, n, min(reps, 3), R)
        arrs = [np.asarray(to_limbs_fast(s)) for s in pool]
        checksum = 0

        def one(i: int):
            arr = arrs[i % len(arrs)]
            if backend == "graft":
                with zk_graft.use_zk_backend("graft"):
                    return zk_graft.msm_limbs(arr, cache)
            return zk_native.msm_limbs(arr, point_limbs[:n])

        one(0)  # warm: jit compile / first-touch outside the timed loop
        t0 = time.perf_counter()
        for i in range(reps):
            pt = one(i)
            checksum ^= pt.x  # consume: the loop body is observable
        dt = time.perf_counter() - t0
        rows.append(
            {
                "n": n,
                "reps": reps,
                "seconds_per_msm": dt / reps,
                "points_per_s": n * reps / dt,
                "checksum": checksum % (1 << 32),
            }
        )
        print(
            f"msm[{backend}] n=2^{n.bit_length() - 1}: "
            f"{rows[-1]['points_per_s']:.1f} points/s "
            f"({rows[-1]['seconds_per_msm']:.3f} s/msm)",
            flush=True,
        )
    return rows


def _bench_ntt(backend: str, sizes, reps: int, rng, R: int):
    from protocol_tpu.utils.limbs import to_limbs_fast
    from protocol_tpu.zk import graft as zk_graft
    from protocol_tpu.zk import plonk

    rows = []
    for n in sizes:
        k = n.bit_length() - 1
        d = plonk.Domain(k)
        pool = [
            np.asarray(
                to_limbs_fast(
                    [int.from_bytes(rng.bytes(32), "little") % R
                     for _ in range(n)]
                )
            )
            for _ in range(min(reps, 3))
        ]
        checksum = 0

        def one(i: int):
            arr = pool[i % len(pool)].copy()  # the native NTT is in-place
            if backend == "graft":
                with zk_graft.use_zk_backend("graft"):
                    return d.ntt_limbs(arr, d.omega, False)
            return d.ntt_limbs(arr, d.omega, False)

        one(0)
        t0 = time.perf_counter()
        for i in range(reps):
            out = one(i)
            checksum ^= int(out[0, 0])
        dt = time.perf_counter() - t0
        butterflies = (n // 2) * k
        rows.append(
            {
                "n": n,
                "reps": reps,
                "seconds_per_ntt": dt / reps,
                "butterflies_per_s": butterflies * reps / dt,
                "checksum": checksum % (1 << 32),
            }
        )
        print(
            f"ntt[{backend}] n=2^{k}: "
            f"{rows[-1]['butterflies_per_s']:.1f} butterflies/s",
            flush=True,
        )
    return rows


def _bench_prove(zk_backend: str, peers: int) -> float:
    """One full epoch prove wall under the given backend."""
    from protocol_tpu.node.bootstrap import FIXED_SET
    from protocol_tpu.node.epoch import Epoch
    from protocol_tpu.node.manager import Manager, ManagerConfig
    from protocol_tpu.prover import prove_job

    cfg = (
        ManagerConfig(prover="plonk", zk_backend=zk_backend)
        if peers == 5
        else ManagerConfig(
            prover="plonk",
            num_neighbours=peers,
            num_iter=1,
            fixed_set=list(FIXED_SET[:peers]),
            zk_backend=zk_backend,
        )
    )
    mgr = Manager(cfg)
    mgr.generate_initial_attestations()
    job = mgr.build_proof_job(Epoch(1))
    return prove_job(job).prove_seconds


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--k-min", type=int, default=10, help="smallest size, log2")
    ap.add_argument(
        "--k-max", type=int, default=16, help="largest native size, log2"
    )
    ap.add_argument(
        "--k-max-graft",
        type=int,
        default=12,
        help="largest graft size, log2 (XLA:CPU MSM reps are slow)",
    )
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument(
        "--backends",
        default="native,graft",
        help="comma list of zk backends to measure",
    )
    ap.add_argument(
        "--prove",
        action="store_true",
        help="also time one full epoch prove per backend (native only "
        "unless --prove-graft; feeds the prove_seconds series)",
    )
    ap.add_argument(
        "--prove-graft",
        action="store_true",
        help="include the graft backend in the --prove leg (hours on CPU)",
    )
    ap.add_argument(
        "--prove-peers", type=int, default=5, help="statement size for --prove"
    )
    ap.add_argument("--smoke", action="store_true", help="CI shape: 2^10, 1 rep")
    ap.add_argument("--n", type=int, default=0, help="bench round number")
    ap.add_argument("--out", default="MSM_smoke.json")
    args = ap.parse_args(argv)

    if args.smoke:
        args.k_min = args.k_max = args.k_max_graft = 10
        args.reps = 1

    _setup_jax_cache()
    from protocol_tpu.crypto.field import MODULUS as R
    from protocol_tpu.zk import kzg

    backends = [b.strip() for b in args.backends.split(",") if b.strip()]
    rng = np.random.default_rng(20_26)
    t0 = time.perf_counter()
    print(f"msm_bench: generating 2^{args.k_max} SRS ladder...", flush=True)
    srs = kzg.Setup.generate(args.k_max, seed=b"msm-bench-srs")
    print(f"msm_bench: SRS in {time.perf_counter() - t0:.1f}s", flush=True)

    entries = []
    for backend in backends:
        k_hi = args.k_max_graft if backend == "graft" else args.k_max
        sizes = [1 << k for k in range(args.k_min, k_hi + 1)]
        msm_rows = _bench_msm(backend, srs, sizes, args.reps, rng, R)
        for row in msm_rows:
            k = row["n"].bit_length() - 1
            entries.append(
                {
                    "metric": f"zk msm throughput ({backend}, n=2^{k}, bn254 G1)",
                    "value": round(row["points_per_s"], 2),
                    "unit": "points/s",
                    "msm_points_per_s": round(row["points_per_s"], 2),
                    "seconds_per_msm": round(row["seconds_per_msm"], 5),
                    "reps": row["reps"],
                    "checksum": row["checksum"],
                }
            )
        ntt_rows = _bench_ntt(backend, sizes, args.reps, rng, R)
        for row in ntt_rows:
            k = row["n"].bit_length() - 1
            entries.append(
                {
                    "metric": f"zk ntt throughput ({backend}, n=2^{k}, fr)",
                    "value": round(row["butterflies_per_s"], 2),
                    "unit": "butterflies/s",
                    "ntt_butterflies_per_s": round(row["butterflies_per_s"], 2),
                    "seconds_per_ntt": round(row["seconds_per_ntt"], 6),
                    "reps": row["reps"],
                    "checksum": row["checksum"],
                }
            )
        if args.prove and (backend != "graft" or args.prove_graft):
            secs = _bench_prove(backend, args.prove_peers)
            entries.append(
                {
                    "metric": (
                        f"plonk epoch prove wall ({backend}, "
                        f"{args.prove_peers} peers)"
                    ),
                    "value": round(secs, 3),
                    "unit": "seconds",
                    "prove_seconds": round(secs, 3),
                }
            )
            print(f"prove[{backend}]: {secs:.2f}s", flush=True)

    report = {
        "config": {
            "k_min": args.k_min,
            "k_max": args.k_max,
            "k_max_graft": args.k_max_graft,
            "reps": args.reps,
            "backends": backends,
            "smoke": bool(args.smoke),
        },
        "n": args.n,
        "entries": entries,
    }
    Path(args.out).write_text(json.dumps(report, indent=2) + "\n")
    print(f"msm_bench: wrote {args.out} ({len(entries)} entries)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
