"""Measure the windowed Pallas gather (ops/gather_window.py) against
the XLA gather at bench scale on the real chip — run when the TPU
tunnel is up (PERF.md §6 queue).

Expected from the primitive measurements (PERF.md §1): ~30 vreg ops per
1024 edges ⇒ low single-digit ms per 50M-edge pass plus ~600 MB HBM
streaming, vs 386 ms for the XLA gather.  Output lands in PERF.md.
"""

import pathlib
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))
import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from protocol_tpu.ops.gather_window import bucket_by_window, gather_windowed

E, N = 50_000_000, 1_048_576
rng = np.random.default_rng(0)
src = rng.integers(0, N, E).astype(np.int32)
w = rng.random(E, dtype=np.float32)
t = rng.random(N, dtype=np.float32)

print("bucketing (host, one-time)...", flush=True)
t0 = time.perf_counter()
b = bucket_by_window(src, w, table_size=N)
print(f"bucketed in {time.perf_counter()-t0:.1f}s, rows={b['n_rows']} "
      f"(pad {(b['n_rows']*1024 - E)/E*100:.2f}%)", flush=True)

wid = jax.device_put(jnp.asarray(b["wid"]))
tbl = jax.device_put(jnp.asarray(t))
loc = jax.device_put(jnp.asarray(b["local"]))
wgt = jax.device_put(jnp.asarray(b["weight"]))

REPS = 8
eps = jnp.float32(1e-38)


@jax.jit
def chain_windowed(wid, tbl, loc, wgt):
    def step(_, acc):
        out = gather_windowed(wid, tbl + acc * eps, loc, wgt, n_rows=b["n_rows"])
        return out[0, 0]
    return lax.fori_loop(0, REPS, step, jnp.float32(0))


@jax.jit
def chain_xla(tbl, src, w):
    def step(_, acc):
        return ((tbl + acc * eps)[src] * w).max()
    return lax.fori_loop(0, REPS, step, jnp.float32(0))


src_d = jax.device_put(jnp.asarray(src))
w_d = jax.device_put(jnp.asarray(w))

for name, fn, args in [
    ("windowed pallas", chain_windowed, (wid, tbl, loc, wgt)),
    ("xla gather", chain_xla, (tbl, src_d, w_d)),
]:
    r = np.asarray(fn(*args))
    t0 = time.perf_counter()
    for _ in range(2):
        r = np.asarray(fn(*args))
    dt = (time.perf_counter() - t0) / 2 / REPS
    print(f"{name}: {dt*1e3:.1f} ms per 50M-edge gather pass", flush=True)
