"""Ingest-storm load generator for the admission plane (ISSUE 7).

Drives ``protocol_tpu.ingest.IngestPlane`` with a pre-signed corpus
(signed by a multi-process generator pool) under four adversarial
mixes and reports the two headline numbers ROADMAP item 2 asks for:
**sustained accepted sigs/s** and **p99 admission latency** — measured
while a churned multi-epoch convergence loop (the real
``EpochPipeline``) runs concurrently in the same process, exactly the
contention the admission tier exists to survive.

Mixes:

- **honest** — unique, validly-signed attestations from whitelisted
  senders; run twice: single-process inline verify (the pre-ISSUE-7
  baseline) and with the verify worker pool (``--workers``);
- **replay** — the honest corpus submitted twice; every second copy
  must die in the dedup cache (``accepted_replays`` must be 0);
- **bad-sig** — corrupted signatures; every one must be rejected by
  the verify tier (``accepted_bad_sigs`` must be 0);
- **hot-sender** — one sender hammering far above the token rate with
  the whitelist off; the rate limiter + spam score must shed them.

Results land as ``INGEST_r<N>.json`` (``--out``), which
``tools/perf_sentinel.py`` folds into its regression series
(``sigs_per_s`` up, ``p99_admission_ms`` down).  ``--smoke`` is the CI
shape (seconds, not minutes); ``--fail-on-shed`` makes honest-mix shed
or any accepted replay/bad-sig a non-zero exit (the CI gate).

NOTE on scaling: worker-pool speedup is a *core-count* story.  On a
1-core container the 4-worker number lands ~1x the single-process
baseline (there is only one core to share); the recorded ``cores``
field says which regime a round measured.  PERF.md §13 tracks both.

Run::

    JAX_PLATFORMS=cpu python bench/ingest_storm.py --workers 4 --out INGEST_r01.json
    JAX_PLATFORMS=cpu python bench/ingest_storm.py --smoke --fail-on-shed --out INGEST_smoke.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
from concurrent.futures import ProcessPoolExecutor
from multiprocessing import get_context
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np


def _scores_row(i: int) -> list[int]:
    """Unique, conservation-respecting score vector #i (sums to the
    SCALE=1000 the structural gate enforces; all entries positive)."""
    d1 = i % 200
    d2 = (i // 200) % 200
    return [200 + d1 - d2, 200 - d1, 200 + d2, 200, 200]


def _sign_range(pairs: list[tuple[str, str]], start: int, count: int) -> list[tuple]:
    """Generator-pool worker: sign ``count`` unique attestations
    (sender round-robins the group).  Returns flat int tuples —
    (sender_idx, i, rx, ry, s) — reassembled by the parent."""
    from protocol_tpu.crypto import calculate_message_hash
    from protocol_tpu.crypto.eddsa import sign
    from protocol_tpu.node.bootstrap import keyset_from_raw

    sks, pks = keyset_from_raw(pairs)
    out = []
    for i in range(start, start + count):
        sender = i % len(pks)
        row = _scores_row(i)
        _, msgs = calculate_message_hash(pks, [row])
        sig = sign(sks[sender], pks[sender], msgs[0])
        out.append((sender, i, sig.big_r.x, sig.big_r.y, sig.s))
    return out


def _build_corpus(count: int, gen_workers: int) -> list:
    """Pre-sign the honest corpus with a multi-process generator pool
    (signing is ~5 ms of Python per attestation — the generator, not
    the plane, would be the bottleneck without the pool)."""
    from protocol_tpu.crypto.babyjubjub import Point
    from protocol_tpu.crypto.eddsa import Signature
    from protocol_tpu.node.attestation import Attestation
    from protocol_tpu.node.bootstrap import FIXED_SET, keyset_from_raw

    _, pks = keyset_from_raw(FIXED_SET)
    chunk = max(1, (count + gen_workers - 1) // gen_workers)
    ranges = [
        (start, min(chunk, count - start)) for start in range(0, count, chunk)
    ]
    if gen_workers > 1 and len(ranges) > 1:
        with ProcessPoolExecutor(
            max_workers=gen_workers, mp_context=get_context("spawn")
        ) as pool:
            parts = list(
                pool.map(
                    _sign_range,
                    [list(FIXED_SET)] * len(ranges),
                    [r[0] for r in ranges],
                    [r[1] for r in ranges],
                )
            )
    else:
        parts = [_sign_range(list(FIXED_SET), s, c) for s, c in ranges]
    corpus = []
    for part in parts:
        for sender, i, rx, ry, s in part:
            corpus.append(
                Attestation(
                    sig=Signature(Point(rx, ry), s),
                    pk=pks[sender],
                    neighbours=list(pks),
                    scores=_scores_row(i),
                )
            )
    return corpus


class _StormStats:
    """Per-run latency/throughput collector (callback-driven)."""

    def __init__(self) -> None:
        self.latencies_ms: list[float] = []
        self.resolved = 0
        self._lock = threading.Lock()

    def callback(self, submitted_at: float):
        def _done(_future) -> None:
            dt = (time.perf_counter() - submitted_at) * 1e3
            with self._lock:
                self.latencies_ms.append(dt)
                self.resolved += 1

        return _done

    def pct(self, q: float) -> float:
        return float(np.percentile(self.latencies_ms, q)) if self.latencies_ms else 0.0


def _run_storm(plane, corpus, *, nonce_base: int | None = None, pace: int = 512):
    """Submit the whole corpus as fast as the plane admits, pacing on
    outstanding futures so an honest run never floods its own bounded
    queue into shedding.  Returns (stats, wall_seconds)."""
    stats = _StormStats()
    t0 = time.perf_counter()
    for k, att in enumerate(corpus):
        while k - stats.resolved > pace:
            time.sleep(0.0005)
        submitted = time.perf_counter()
        nonce = None if nonce_base is None else nonce_base + k
        plane.submit(att, nonce=nonce).add_done_callback(stats.callback(submitted))
    plane.drain(timeout=600)
    return stats, time.perf_counter() - t0


def _fresh_plane(manager, *, workers: int, whitelist: bool = True,
                 rate: float = 1e9, burst: float = 1e9, queue_max: int = 1024,
                 batch_size: int = 64):
    from protocol_tpu.ingest import IngestPlane, IngestPlaneConfig
    from protocol_tpu.ingest.ratelimit import RateLimitConfig

    wl = (
        frozenset((pk.point.x, pk.point.y) for pk in manager._group_pks)
        if whitelist
        else frozenset()
    )
    return IngestPlane(
        manager,
        IngestPlaneConfig(
            workers=workers,
            batch_size=batch_size,
            submit_queue_max=queue_max,
            rate=RateLimitConfig(rate=rate, burst=burst, whitelist=wl),
        ),
    ).start()


def _fresh_manager():
    from protocol_tpu.node.manager import Manager, ManagerConfig

    return Manager(ManagerConfig(prover="commitment"))


def _epoch_loop_thread(peers: int, edges: int, epochs: int, result: dict):
    """The concurrent churned convergence loop: the real EpochPipeline
    over a synthetic open graph (mirrors tools/epoch_pipe.py — peer
    hashes are row ids so warm-start/delta plumbing runs for real)."""
    from protocol_tpu.models.graphs import scale_free
    from protocol_tpu.node.epoch import Epoch
    from protocol_tpu.node.manager import Manager, ManagerConfig
    from protocol_tpu.node.pipeline import EpochPipeline
    from protocol_tpu.trust.graph import TrustGraph

    class _ChurnManager(Manager):
        def __init__(self, g):
            super().__init__(
                ManagerConfig(
                    backend="tpu-windowed",
                    prover="commitment",
                    plan_delta_max_churn=0.25,
                )
            )
            self._graph = g
            self._rng = np.random.default_rng(23)

        def churn(self, fraction: float) -> int:
            g = self._graph
            k = max(1, int(g.nnz * fraction))
            idx = self._rng.choice(g.nnz, k, replace=False)
            dst = g.dst.copy()
            dst[idx] = self._rng.integers(0, g.n, k)
            while (bad := dst[idx] == g.src[idx]).any():
                dst[idx[bad]] = self._rng.integers(0, g.n, int(bad.sum()))
            self._graph = TrustGraph(g.n, g.src, dst, g.weight, g.pre_trusted)
            self._dirty_hashes.update(int(s) for s in np.unique(g.src[idx]))
            return k

        def build_graph(self):
            self._id_order = list(range(self._graph.n))
            return self._graph

    manager = _ChurnManager(scale_free(peers, edges, seed=7))
    per_epoch = []
    try:
        with EpochPipeline(manager, alpha=0.1, tol=1e-6, max_iter=80) as pipe:
            for k in range(epochs):
                if k:
                    manager.churn(0.01)
                t0 = time.perf_counter()
                pipe.submit(Epoch(k))
                landed = pipe.drain(timeout=600)
                outcome = pipe.outcomes.get(k)
                per_epoch.append(
                    {
                        "epoch": k,
                        "seconds": round(time.perf_counter() - t0, 4),
                        "landed": bool(landed and outcome and outcome.error is None),
                    }
                )
            result["coalesced"] = pipe.coalesced
    except Exception as exc:  # noqa: BLE001 - report, don't kill the bench
        result["error"] = repr(exc)
    result["per_epoch"] = per_epoch
    result["all_landed"] = all(e["landed"] for e in per_epoch) and len(
        per_epoch
    ) == epochs


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--count", type=int, default=2000, help="honest corpus size")
    ap.add_argument("--workers", type=int, default=4, help="verify worker processes")
    ap.add_argument("--gen-workers", type=int, default=4, help="signer processes")
    ap.add_argument("--epochs", type=int, default=3, help="concurrent churned epochs")
    ap.add_argument("--peers", type=int, default=20_000)
    ap.add_argument("--edges", type=int, default=120_000)
    ap.add_argument("--mix", default="all", choices=["all", "honest"])
    ap.add_argument("--smoke", action="store_true", help="CI shape: seconds, not minutes")
    ap.add_argument("--fail-on-shed", action="store_true",
                    help="exit 1 on honest-mix shed or any accepted replay/bad-sig")
    ap.add_argument("--out", default="INGEST_smoke.json")
    args = ap.parse_args(argv)
    if args.smoke:
        args.count = min(args.count, 150)
        args.workers = min(args.workers, 2)
        args.gen_workers = min(args.gen_workers, 2)
        args.epochs = min(args.epochs, 2)
        args.peers, args.edges = 4000, 24_000

    from protocol_tpu.obs.metrics import EPOCH_TICKS_DROPPED

    print(f"ingest_storm: signing {args.count}-attestation corpus "
          f"({args.gen_workers} generator processes)...")
    t0 = time.perf_counter()
    corpus = _build_corpus(args.count, args.gen_workers)
    print(f"ingest_storm: corpus ready in {time.perf_counter() - t0:.1f}s")

    report: dict = {
        "n": 1,
        "bench": "ingest_storm",
        "cores": os.cpu_count(),
        "config": {
            "count": args.count,
            "workers": args.workers,
            "epochs": args.epochs,
            "smoke": bool(args.smoke),
        },
        "entries": [],
    }
    shape = f"{args.count} sigs"
    failures: list[str] = []

    # -- honest, single-process baseline (workers=0, no epoch loop) ----
    manager = _fresh_manager()
    with _fresh_plane(manager, workers=0) as plane:
        stats, wall = _run_storm(plane, corpus)
        baseline = plane.accepted / wall if wall > 0 else 0.0
        report["entries"].append(
            {
                "metric": f"ingest-storm accepted sigs/s (honest, {shape}, single-process)",
                "sigs_per_s": round(baseline, 1),
                "p99_admission_ms": round(stats.pct(99), 2),
                "p50_admission_ms": round(stats.pct(50), 2),
                "accepted": plane.accepted,
                "shed": plane.shed,
                "rejections": plane.rejections,
            }
        )
        if plane.shed or plane.rejections:
            failures.append(f"single-process honest mix shed/rejected: {plane.stats()}")
    print(f"ingest_storm: single-process honest {baseline:.0f} accepted sigs/s")

    # -- honest, worker pool alone (pure worker-scaling measure) -------
    manager = _fresh_manager()
    with _fresh_plane(manager, workers=args.workers) as plane:
        warm = corpus[0]
        plane.pool.verify(plane._pks_hash, [
            (warm.sig.big_r.x, warm.sig.big_r.y, warm.sig.s,
             warm.pk.point.x, warm.pk.point.y, tuple(warm.scores))
        ])
        stats, wall = _run_storm(plane, corpus)
        pooled = plane.accepted / wall if wall > 0 else 0.0
        report["entries"].append(
            {
                "metric": f"ingest-storm accepted sigs/s (honest, {shape}, "
                          f"{args.workers} workers)",
                "sigs_per_s": round(pooled, 1),
                "p99_admission_ms": round(stats.pct(99), 2),
                "p50_admission_ms": round(stats.pct(50), 2),
                "accepted": plane.accepted,
                "shed": plane.shed,
                "rejections": plane.rejections,
            }
        )
        if plane.shed or plane.rejections:
            failures.append(f"worker-pool honest mix shed/rejected: {plane.stats()}")
    report["speedup_vs_single_process"] = (
        round(pooled / baseline, 2) if baseline else None
    )
    print(
        f"ingest_storm: {args.workers}-worker honest {pooled:.0f} accepted sigs/s "
        f"({report['speedup_vs_single_process']}x vs single-process on "
        f"{report['cores']} core(s))"
    )

    # -- honest headline: worker pool + concurrent churned epoch loop --
    dropped0 = EPOCH_TICKS_DROPPED.value()
    epoch_result: dict = {}
    epoch_thread = threading.Thread(
        target=_epoch_loop_thread,
        args=(args.peers, args.edges, args.epochs, epoch_result),
        daemon=True,
    )
    manager = _fresh_manager()
    with _fresh_plane(manager, workers=args.workers) as plane:
        # Warm the pool (spawn + per-worker crypto import) off the
        # clock: the steady-state number should not bill process
        # startup against admission latency.
        warm = corpus[0]
        plane.pool.verify(
            plane._pks_hash,
            [
                (
                    warm.sig.big_r.x,
                    warm.sig.big_r.y,
                    warm.sig.s,
                    warm.pk.point.x,
                    warm.pk.point.y,
                    tuple(warm.scores),
                )
            ]
            * max(1, args.workers),
        )
        epoch_thread.start()
        stats, wall = _run_storm(plane, corpus)
        headline = plane.accepted / wall if wall > 0 else 0.0
        entry = {
            "metric": f"ingest-storm accepted sigs/s (honest, {shape}, "
                      f"{args.workers} workers + churned epoch loop)",
            "sigs_per_s": round(headline, 1),
            "p99_admission_ms": round(stats.pct(99), 2),
            "p50_admission_ms": round(stats.pct(50), 2),
            "accepted": plane.accepted,
            "shed": plane.shed,
            "rejections": plane.rejections,
        }
        report["entries"].append(entry)
        if plane.shed or plane.rejections:
            failures.append(
                f"honest mix under epoch loop shed/rejected: {plane.stats()}"
            )
        epoch_thread.join(timeout=600)
    report["throughput_retained_under_epoch_loop"] = (
        round(headline / pooled, 2) if pooled else None
    )
    epoch_result["dropped_ticks"] = EPOCH_TICKS_DROPPED.value() - dropped0
    report["epoch_loop"] = epoch_result
    if not epoch_result.get("all_landed"):
        failures.append(f"concurrent epoch loop did not land every epoch: {epoch_result}")
    if epoch_result["dropped_ticks"]:
        failures.append(f"epoch loop dropped {epoch_result['dropped_ticks']} tick(s)")
    print(
        f"ingest_storm: under churned epoch loop {headline:.0f} accepted sigs/s "
        f"(p99 {stats.pct(99):.1f} ms, "
        f"{report['throughput_retained_under_epoch_loop']}x of the uncontended "
        f"pool); epoch loop {'ok' if epoch_result.get('all_landed') else 'FAILED'}"
    )

    if args.mix == "all":
        adversarial: dict = {}
        # Replay: the corpus twice; second copies must all dedup out.
        manager = _fresh_manager()
        with _fresh_plane(manager, workers=0) as plane:
            _run_storm(plane, corpus)
            first_accepted = plane.accepted
            _run_storm(plane, corpus)
            adversarial["replay"] = {
                "accepted_first_pass": first_accepted,
                "accepted_replays": plane.accepted - first_accepted,
                "duplicates_rejected": plane.rejections.get("duplicate", 0),
            }
            if plane.accepted != first_accepted:
                failures.append(f"replays accepted: {adversarial['replay']}")

        # Bad signatures: corrupt s; every one must be rejected.
        from protocol_tpu.crypto.eddsa import Signature
        from protocol_tpu.node.attestation import Attestation

        bad_corpus = [
            Attestation(
                sig=Signature(a.sig.big_r, a.sig.s + 1),
                pk=a.pk,
                neighbours=a.neighbours,
                scores=a.scores,
            )
            for a in corpus[: max(50, args.count // 4)]
        ]
        manager = _fresh_manager()
        with _fresh_plane(manager, workers=0) as plane:
            _run_storm(plane, bad_corpus)
            adversarial["bad_sig"] = {
                "submitted": len(bad_corpus),
                "accepted_bad_sigs": plane.accepted,
                "rejected": plane.rejections.get("bad-signature", 0),
            }
            if plane.accepted:
                failures.append(f"bad signatures accepted: {adversarial['bad_sig']}")

        # Hot sender: whitelist off, tight bucket; the flood must shed
        # at the rate limiter, not reach the verify tier.
        hot = [corpus[i] for i in range(0, len(corpus), 5)]  # one sender
        manager = _fresh_manager()
        with _fresh_plane(
            manager, workers=0, whitelist=False, rate=20.0, burst=25.0
        ) as plane:
            _run_storm(plane, hot)
            adversarial["hot_sender"] = {
                "submitted": len(hot),
                "accepted": plane.accepted,
                "rate_limited": plane.rejections.get("rate-limited", 0),
                "spam_score": plane.rejections.get("spam-score", 0),
            }
            limited = (
                adversarial["hot_sender"]["rate_limited"]
                + adversarial["hot_sender"]["spam_score"]
            )
            if len(hot) > 30 and not limited:
                failures.append(f"hot sender never limited: {adversarial['hot_sender']}")
        report["adversarial"] = adversarial
        print(f"ingest_storm: adversarial mixes {json.dumps(adversarial)}")

    report["failures"] = failures
    Path(args.out).parent.mkdir(parents=True, exist_ok=True)
    Path(args.out).write_text(json.dumps(report, indent=2) + "\n")
    print(f"ingest_storm: report at {args.out}")
    if failures and args.fail_on_shed:
        for f in failures:
            print(f"ingest_storm: FAIL — {f}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
