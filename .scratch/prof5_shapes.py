"""Map the dynamic_gather support surface: which (table, axis) shapes compile,
plus scalar dynamic loads, dynamic-row accumulate, sublane roll — the
primitives available for kernel design. Also XLA converge_csr at bench scale."""
import time
import jax, jax.numpy as jnp, numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

rng = np.random.default_rng(0)

def bench(name, fn, *args, reps=3):
    try:
        g = jax.jit(lambda *a: fn(*a).max())
        float(g(*args))
        t0 = time.perf_counter()
        for _ in range(reps):
            float(g(*args))
        dt = (time.perf_counter() - t0) / reps
        print(f"{name}: {dt*1000:.2f} ms", flush=True)
    except Exception as e:
        s = str(e).splitlines()
        s = s[0][:140] if s else type(e).__name__
        print(f"{name}: FAILED — {s}", flush=True)

def gather_axis(rows, lanes, axis):
    t = jax.device_put(jnp.asarray(rng.random(rows * lanes, dtype=np.float32).reshape(rows, lanes)))
    hi = rows if axis == 0 else lanes
    ix = jax.device_put(jnp.asarray(rng.integers(0, hi, (rows, lanes)).astype(np.int32)))
    def k(t_ref, i_ref, o_ref):
        o_ref[:] = jnp.take_along_axis(t_ref[:], i_ref[:], axis=axis)
    call = pl.pallas_call(
        k,
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM), pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((rows, lanes), jnp.float32),
    )
    bench(f"axis{axis} ({rows},{lanes})", call, t, ix)

for rows, lanes in [(8, 128), (64, 128), (512, 128), (1024, 128), (4096, 128)]:
    gather_axis(rows, lanes, 0)
for rows, lanes in [(8, 1024), (128, 8192), (1024, 1024), (8192, 256)]:
    gather_axis(rows, lanes, 1)

# axis1 throughput at scale: grid over a big stream, table-shaped (8192,128) blocks
E = 2**25  # 33.5M
t2 = jax.device_put(jnp.asarray(rng.random(1 << 20, dtype=np.float32).reshape(8192, 128)))
cb = jax.device_put(jnp.asarray(rng.integers(0, 128, (E // 128, 128)).astype(np.int32)))
wb = jax.device_put(jnp.asarray(rng.random((E // 128, 128), dtype=np.float32)))

def k_stream(t_ref, c_ref, w_ref, o_ref):
    o_ref[:] = w_ref[:] * jnp.take_along_axis(t_ref[:], c_ref[:], axis=1)

stream = pl.pallas_call(
    k_stream,
    grid=(E // (8192 * 128),),
    in_specs=[
        pl.BlockSpec((8192, 128), lambda i: (0, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((8192, 128), lambda i: (i, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((8192, 128), lambda i: (i, 0), memory_space=pltpu.VMEM),
    ],
    out_specs=pl.BlockSpec((8192, 128), lambda i: (i, 0), memory_space=pltpu.VMEM),
    out_shape=jax.ShapeDtypeStruct((E // 128, 128), jnp.float32),
)
bench("axis1 streamed 33.5M (row-local gather+mul)", stream, t2, cb, wb)

# sublane roll (static) + select — Benes building blocks
def k_roll(x_ref, m_ref, o_ref):
    x = x_ref[:]
    for d in (1, 2, 4):
        p = jnp.roll(x, d, axis=0)
        x = jnp.where(m_ref[:] > d, p, x)
    o_ref[:] = x
x8 = jax.device_put(jnp.asarray(rng.random((8192, 128), dtype=np.float32)))
m8 = jax.device_put(jnp.asarray(rng.integers(0, 8, (8192, 128)).astype(np.int32)))
roll = pl.pallas_call(
    k_roll,
    in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM), pl.BlockSpec(memory_space=pltpu.VMEM)],
    out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
    out_shape=jax.ShapeDtypeStruct((8192, 128), jnp.float32),
)
bench("roll+select x3 (8192,128)", roll, x8, m8)

# dynamic-row accumulate: o[r, :] += v for scalar r from SMEM
def k_acc(r_ref, x_ref, o_ref):
    o_ref[:] = jnp.zeros_like(o_ref)
    def body(i, _):
        r = r_ref[i]
        o_ref[r, :] += x_ref[i, :]
        return 0
    jax.lax.fori_loop(0, 64, body, 0)
racc = jax.device_put(jnp.asarray(rng.integers(0, 128, 64).astype(np.int32)))
xacc = jax.device_put(jnp.asarray(rng.random((64, 128), dtype=np.float32)))
acc = pl.pallas_call(
    k_acc,
    in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM), pl.BlockSpec(memory_space=pltpu.VMEM)],
    out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
    out_shape=jax.ShapeDtypeStruct((128, 128), jnp.float32),
)
bench("dynamic-row accumulate (64 rows)", acc, racc, xacc)
