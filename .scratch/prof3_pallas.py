"""Can Mosaic vectorize a gather from a VMEM-resident table?

Table t: 1M f32 (4 MB) resident in VMEM as (8192, 128).
Edge stream: idx blocks; out[e] = t[idx[e]].
Try several lowering strategies and time whichever compiles.
"""
import sys, time
import jax, jax.numpy as jnp, numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

E = 2**24  # 16M edges for the micro-bench
N = 1 << 20
BLK = 2**17  # edges per grid step (0.5 MB idx)

rng = np.random.default_rng(0)
idx = rng.integers(0, N, E).astype(np.int32)
t = rng.random(N, dtype=np.float32)

t2d = jax.device_put(jnp.asarray(t.reshape(N // 128, 128)))
idx2d = jax.device_put(jnp.asarray(idx.reshape(E // 128, 128)))
_ = float(jnp.sum(t2d))

grid = (E // BLK,)
R = BLK // 128  # rows per block


def make(kernel_body):
    return pl.pallas_call(
        kernel_body,
        grid=grid,
        in_specs=[
            pl.BlockSpec((N // 128, 128), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((R, 128), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((R, 128), lambda i: (i, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((E // 128, 128), jnp.float32),
    )


def v1_direct(t_ref, i_ref, o_ref):
    ix = i_ref[:]
    rows = ix // 128
    cols = ix % 128
    o_ref[:] = t_ref[rows, cols]


def v2_take(t_ref, i_ref, o_ref):
    flat = t_ref[:].reshape(-1)
    o_ref[:] = jnp.take(flat, i_ref[:], axis=0)


def v3_take_along(t_ref, i_ref, o_ref):
    # gather rows via take on axis 0, then select lane via take_along_axis
    ix = i_ref[:]
    rows = ix // 128
    cols = ix % 128
    picked = jnp.take(t_ref[:], rows, axis=0)  # (R,128,128)?? no — rows is 2d
    o_ref[:] = jnp.take_along_axis(picked, cols[..., None], axis=-1)[..., 0]


def bench(name, fn):
    try:
        g = jax.jit(lambda t, i: fn(t, i).max())
        r = float(g(t2d, idx2d))
        t0 = time.perf_counter()
        reps = 5
        for _ in range(reps):
            r = float(g(t2d, idx2d))
        dt = (time.perf_counter() - t0) / reps
        print(f"{name}: {dt*1000:.2f} ms  ({E/dt/1e9:.2f} Gelem/s)", flush=True)
    except Exception as e:
        msg = str(e).split(chr(10))[0][:200]
        print(f"{name}: FAILED — {type(e).__name__}: {msg}", flush=True)


for name, body in [("v1 direct t[rows,cols]", v1_direct),
                   ("v2 take(flat)", v2_take),
                   ("v3 take rows + take_along lanes", v3_take_along)]:
    bench(name, make(body))

# XLA baseline at same size
g = jax.jit(lambda t, i: jnp.take(t.reshape(-1), i.reshape(-1)).max())
float(g(t2d, idx2d))
t0 = time.perf_counter()
for _ in range(3):
    float(g(t2d, idx2d))
print(f"XLA gather baseline: {(time.perf_counter()-t0)/3*1000:.2f} ms", flush=True)
