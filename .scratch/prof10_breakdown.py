"""Chained component breakdown of power_step_csr at full bench scale
(50M edges, 1M peers): where do 447 ms/iter actually go?"""
import sys, time
sys.path.insert(0, "/root/repo")
import jax, jax.numpy as jnp, numpy as np
from jax import lax

from protocol_tpu.ops.sparse import rowsum_sorted, power_step_csr, _ds_cumsum_axis1, _compensated_cumsum

rng = np.random.default_rng(0)
E, N = 50_000_000, 1_000_000
REPS = 8

t_full = jax.device_put(jnp.asarray(rng.random(N, dtype=np.float32)))
src = jax.device_put(jnp.asarray(rng.integers(0, N, E).astype(np.int32)))
w = jax.device_put(jnp.asarray(rng.random(E, dtype=np.float32)))
contrib = jax.device_put(jnp.asarray(rng.random(E, dtype=np.float32)))
row_ptr = jax.device_put(jnp.asarray(
    np.searchsorted(np.sort(rng.integers(0, N, E)), np.arange(N + 1)).astype(np.int32)))
p = jax.device_put(jnp.full(N, 1.0 / N, np.float32))
dang = jax.device_put(jnp.zeros(N, np.float32))


def timeit(name, fn, *args, reps=2):
    f = jax.jit(fn)
    r = np.asarray(f(*args))
    t0 = time.perf_counter()
    for _ in range(reps):
        r = np.asarray(f(*args))
    dt = (time.perf_counter() - t0) / reps
    print(f"{name}: {dt/REPS*1e3:.1f} ms/iter  ({dt*1e3:.0f} ms for {REPS})", flush=True)


def chain(body):
    def run(*args):
        def step(_, acc):
            return body(acc, *args)
        return lax.fori_loop(0, REPS, step, jnp.float32(0))
    return run

timeit("gather t[src]", chain(lambda acc, t, s: acc + t[s].sum()), t_full, src)
timeit("w*t[src]", chain(lambda acc, t, s, w: acc + (w * t[s]).sum()), t_full, src, w)
timeit("rowsum_sorted", chain(lambda acc, c, rp: acc + rowsum_sorted(c, rp).sum()), contrib, row_ptr)
timeit("ds_cumsum blocks only", chain(
    lambda acc, c: acc + _ds_cumsum_axis1(c.reshape(-1, 2048))[0][:, -1].sum()), contrib)
timeit("full power_step_csr", chain(
    lambda acc, s, rp, w, t, p, d: acc + power_step_csr(s, rp, w, t, p, d, 0.1).sum()),
    src, row_ptr, w, t_full, p, dang)
timeit("gather+rowsum (no step extras)", chain(
    lambda acc, t, s, w, rp: acc + rowsum_sorted(w * t[s], rp).sum()),
    t_full, src, w, row_ptr)
