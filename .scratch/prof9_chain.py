"""Clean timing: repeat kernels inside one jit (fori_loop) + forced host
transfer, so async-dispatch / tunnel round-trip artifacts cancel.
Measures: axis1 lane gather (8192,128), axis0 (8,128) sublane gather,
XLA gather at 50M, and a prototype windowed-gather kernel block."""
import time
import jax, jax.numpy as jnp, numpy as np
from jax import lax
from jax.experimental import pallas as pl

rng = np.random.default_rng(0)
R, L = 8192, 128
REPS = 40


def timeit(name, jitted, *args, reps=3):
    r = np.asarray(jax.tree.leaves(jitted(*args))[0])
    t0 = time.perf_counter()
    for _ in range(reps):
        r = np.asarray(jax.tree.leaves(jitted(*args))[0])
    dt = (time.perf_counter() - t0) / reps
    print(f"{name}: {dt*1e3:.2f} ms total, {dt/REPS*1e3:.3f} ms/call", flush=True)


# 1. axis1 lane gather chained 40x
t2d = jax.device_put(jnp.asarray(rng.random((R, L), dtype=np.float32)))
idx1 = jax.device_put(jnp.asarray(rng.integers(0, L, (R, L)).astype(np.int32)))

g1 = pl.pallas_call(
    lambda t_ref, i_ref, o_ref: o_ref.__setitem__(
        slice(None), jnp.take_along_axis(t_ref[:], i_ref[:], axis=1)),
    out_shape=jax.ShapeDtypeStruct((R, L), jnp.float32),
)

@jax.jit
def chain1(t, i):
    return lax.fori_loop(0, REPS, lambda _, x: g1(x, i), t)

timeit("axis1 lane-gather (8192,128) x40 chained", chain1, t2d, idx1)

# 2. windowed-gather prototype: full block (512,128) edges, VMEM table,
#    in-kernel loop over 64 vregs, 8-way select per vreg.
BR = 512  # block rows
wid = rng.integers(0, R // 8, BR // 8).astype(np.int32)  # window per vreg-row
src_local = rng.integers(0, 1024, (BR, L)).astype(np.int32)  # within-window
w_np = rng.random((BR, L), dtype=np.float32)

def windowed_kernel(wid_ref, t_ref, s_ref, w_ref, o_ref):
    out = jnp.zeros((BR, L), jnp.float32)
    for v in range(BR // 8):
        win = t_ref[pl.ds(wid_ref[v] * 8, 8), :]          # (8,128) dynamic slice
        sl = s_ref[pl.ds(v * 8, 8), :]                     # local idx (8,128)
        sub = sl // 128                                    # sublane in window
        lane = sl % 128                                    # lane in window
        acc = jnp.zeros((8, L), jnp.float32)
        for k in range(8):
            rowk = jnp.broadcast_to(win[k:k+1, :], (8, L))
            g = jnp.take_along_axis(rowk, lane, axis=1)
            acc = jnp.where(sub == k, g, acc)
        out = out.at[v*8:(v+1)*8, :].set(acc * w_ref[pl.ds(v*8, 8), :])
    o_ref[:] = out

wk = pl.pallas_call(
    windowed_kernel,
    grid=(1,),
    in_specs=[
        pl.BlockSpec(memory_space=pl.ANY) if False else pl.BlockSpec((BR // 8,), lambda i: (0,)),
        pl.BlockSpec((R, L), lambda i: (0, 0)),
        pl.BlockSpec((BR, L), lambda i: (0, 0)),
        pl.BlockSpec((BR, L), lambda i: (0, 0)),
    ],
    out_specs=pl.BlockSpec((BR, L), lambda i: (0, 0)),
    out_shape=jax.ShapeDtypeStruct((BR, L), jnp.float32),
)

wid_d = jax.device_put(jnp.asarray(wid))
s_d = jax.device_put(jnp.asarray(src_local))
w_d = jax.device_put(jnp.asarray(w_np))

try:
    out = np.asarray(jax.jit(wk)(wid_d, t2d, s_d, w_d))
    tn = np.asarray(t2d)
    gsrc = wid[np.arange(BR) // 8] * 1024 + src_local.reshape(BR, L)[np.arange(BR)[:, None], np.arange(L)[None, :]]
    exp = tn.reshape(-1)[wid[np.arange(BR)[:, None] // 8] * 1024 + src_local] * w_np
    print("windowed kernel correct:", np.allclose(out, exp), flush=True)

    @jax.jit
    def chainw(wid, t, s, w):
        def body(_, x):
            return wk(wid, t, s, x)
        return lax.fori_loop(0, REPS, body, w)
    timeit("windowed-gather (512,128) block x40 chained", chainw, wid_d, t2d, s_d, w_d)
except Exception as e:
    s = str(e).splitlines()
    print(f"windowed kernel: FAILED — {type(e).__name__}: {s[0][:200] if s else ''}", flush=True)

# 3. XLA gather 50M chained x4 (too slow for 40)
E = 50_000_000
N = R * L
t_full = jax.device_put(jnp.asarray(rng.random(N, dtype=np.float32)))
src = jax.device_put(jnp.asarray(rng.integers(0, N, E).astype(np.int32)))

@jax.jit
def chainx(t, s):
    return lax.fori_loop(0, 4, lambda _, x: jnp.bincount(jnp.zeros(1, jnp.int32), weights=x[s][:1], length=1)[0] * 0 + x, t)

# simpler: sum of gathers
@jax.jit
def chainx2(t, s):
    def body(_, acc):
        return acc + t[s].sum()
    return lax.fori_loop(0, 4, body, jnp.float32(0))

r = float(chainx2(t_full, src)); t0 = time.perf_counter()
for _ in range(3):
    r = float(chainx2(t_full, src))
dt = (time.perf_counter() - t0) / 3
print(f"XLA gather 50M x4 chained: {dt*1e3:.1f} ms total, {dt/4*1e3:.1f} ms/gather", flush=True)
