"""Decision measurements: (a) converge_csr at bench scale, (b) XLA
gather/scatter vs index locality and table size, (c) rowsum_sorted cost."""
import time
import jax, jax.numpy as jnp, numpy as np

rng = np.random.default_rng(0)

def bench(name, fn, *args, reps=3):
    try:
        g = jax.jit(fn)
        r = jax.tree.map(np.asarray, g(*args))
        t0 = time.perf_counter()
        for _ in range(reps):
            r = jax.tree.map(np.asarray, g(*args))
        dt = (time.perf_counter() - t0) / reps
        print(f"{name}: {dt*1000:.1f} ms", flush=True)
    except Exception as e:
        s = str(e).splitlines()
        print(f"{name}: FAILED — {s[0][:140] if s else type(e).__name__}", flush=True)

E, N = 50_000_000, 1_000_000

# (b) locality experiments at 8M edges
Es = 8_000_000
t_small = jax.device_put(jnp.asarray(rng.random(N, dtype=np.float32)))
idx_rand = jax.device_put(jnp.asarray(rng.integers(0, N, Es).astype(np.int32)))
# localized: indices within 16K-wide windows, window advancing with position
base = (np.arange(Es) // (Es // 64)) * (N // 64)
idx_loc = jax.device_put(jnp.asarray((base + rng.integers(0, N // 64, Es)).astype(np.int32)))
t_tiny = jax.device_put(jnp.asarray(rng.random(16384, dtype=np.float32)))
idx_tiny = jax.device_put(jnp.asarray(rng.integers(0, 16384, Es).astype(np.int32)))
_ = float(jnp.sum(t_small))

bench("gather 8M from 1M table, random idx", lambda t, i: t[i].max(), t_small, idx_rand)
bench("gather 8M from 1M table, 16K-local idx", lambda t, i: t[i].max(), t_small, idx_loc)
bench("gather 8M from 16K table", lambda t, i: t[i].max(), t_tiny, idx_tiny)

v8 = jax.device_put(jnp.asarray(rng.random(Es, dtype=np.float32)))
seg_sorted = jax.device_put(jnp.asarray(np.sort(rng.integers(0, N, Es)).astype(np.int32)))
seg_small = jax.device_put(jnp.asarray(np.sort(rng.integers(0, 16384, Es)).astype(np.int32)))
bench("segsum 8M -> 1M sorted", lambda v, s: jax.ops.segment_sum(v, s, num_segments=N, indices_are_sorted=True).max(), v8, seg_sorted)
bench("segsum 8M -> 16K sorted", lambda v, s: jax.ops.segment_sum(v, s, num_segments=16384, indices_are_sorted=True).max(), v8, seg_small)

# scatter 1M values into a 50M array (expand-trick boundary scatter)
pos = jax.device_put(jnp.asarray(np.sort(rng.choice(E, N, replace=False)).astype(np.int32)))
vals = jax.device_put(jnp.asarray(rng.random(N, dtype=np.float32)))
bench("scatter-add 1M into 50M", lambda p, v: jnp.zeros(E, jnp.float32).at[p].add(v).max(), pos, vals)

# (c) rowsum_sorted at full scale
from protocol_tpu.ops.sparse import rowsum_sorted
contrib = jax.device_put(jnp.asarray(rng.random(E, dtype=np.float32)))
row_ptr = jax.device_put(jnp.asarray(np.searchsorted(np.sort(rng.integers(0, N, E)), np.arange(N + 1)).astype(np.int32)))
bench("rowsum_sorted 50M->1M", lambda c, rp: rowsum_sorted(c, rp).max(), contrib, row_ptr)

# (a) converge_csr at bench scale — the repo's fast path claim
from protocol_tpu.models.graphs import scale_free
from protocol_tpu.trust.graph import TrustGraph
from protocol_tpu.ops.sparse import converge_csr

graph = scale_free(N, E, seed=7)
g0 = graph.drop_self_edges()
w, dangling = g0.row_normalized()
g = TrustGraph(g0.n, g0.src, g0.dst, w, graph.pre_trusted).sorted_by_dst()
p = graph.pre_trust_vector()
rp = np.searchsorted(g.dst, np.arange(N + 1)).astype(np.int32)
args = tuple(jax.device_put(jnp.asarray(x)) for x in
             (g.src, rp, g.weight, p, p, dangling.astype(np.float32)))
_ = float(jnp.sum(args[2]))
bench("converge_csr 40 iters full bench scale",
      lambda *a: converge_csr(*a, alpha=jnp.float32(0.1), tol=0.0, max_iter=40)[0],
      *args, reps=1)
