"""Micro-bench tpu.dynamic_gather via Pallas take_along_axis with the
supported same-shape (8192,128) form, both axes, plus full-scale XLA
component timings for one converge_csr step (gather / rowsum / step).
"""
import time
import jax, jax.numpy as jnp, numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

R, L = 8192, 128  # one block = 1M elements
N = R * L


def bench(name, fn, *args, reps=10):
    try:
        r = jax.block_until_ready(fn(*args))
        t0 = time.perf_counter()
        for _ in range(reps):
            r = jax.block_until_ready(fn(*args))
        dt = (time.perf_counter() - t0) / reps
        print(f"{name}: {dt*1e3:.3f} ms", flush=True)
        return r, dt
    except Exception as e:
        s = str(e).splitlines()
        print(f"{name}: FAILED — {type(e).__name__}: {s[0][:160] if s else ''}", flush=True)
        return None, None


rng = np.random.default_rng(0)
t2d = jax.device_put(jnp.asarray(rng.random((R, L), dtype=np.float32)))

# ---- single-block kernels: gather axis0 (sublane) and axis1 (lane) ----
def k_ax0(t_ref, i_ref, o_ref):
    o_ref[:] = jnp.take_along_axis(t_ref[:], i_ref[:], axis=0)

def k_ax1(t_ref, i_ref, o_ref):
    o_ref[:] = jnp.take_along_axis(t_ref[:], i_ref[:], axis=1)

idx0 = jax.device_put(jnp.asarray(rng.integers(0, R, (R, L)).astype(np.int32)))
idx1 = jax.device_put(jnp.asarray(rng.integers(0, L, (R, L)).astype(np.int32)))

one = pl.pallas_call(
    k_ax0,
    out_shape=jax.ShapeDtypeStruct((R, L), jnp.float32),
)
r, dt = bench("pallas dynamic_gather axis0, 1M elems single call", jax.jit(one), t2d, idx0)
if r is not None:
    expect = np.asarray(t2d)[np.asarray(idx0), np.arange(L)[None, :]]
    print("  correct:", bool(np.array_equal(np.asarray(r), expect)), flush=True)

one1 = pl.pallas_call(
    k_ax1,
    out_shape=jax.ShapeDtypeStruct((R, L), jnp.float32),
)
r, dt = bench("pallas dynamic_gather axis1, 1M elems single call", jax.jit(one1), t2d, idx1)
if r is not None:
    expect = np.asarray(t2d)[np.arange(R)[:, None], np.asarray(idx1)]
    print("  correct:", bool(np.array_equal(np.asarray(r), expect)), flush=True)

# ---- streamed: 48 blocks (49M edges), table pinned, idx streamed ----
B = 48
idx_big = jax.device_put(jnp.asarray(rng.integers(0, R, (B * R, L)).astype(np.int32)))
w_big = jax.device_put(jnp.asarray(rng.random((B * R, L), dtype=np.float32)))

def k_stream(t_ref, i_ref, w_ref, o_ref):
    o_ref[:] = w_ref[:] * jnp.take_along_axis(t_ref[:], i_ref[:], axis=0)

stream = pl.pallas_call(
    k_stream,
    grid=(B,),
    in_specs=[
        pl.BlockSpec((R, L), lambda i: (0, 0)),
        pl.BlockSpec((R, L), lambda i: (i, 0)),
        pl.BlockSpec((R, L), lambda i: (i, 0)),
    ],
    out_specs=pl.BlockSpec((R, L), lambda i: (i, 0)),
    out_shape=jax.ShapeDtypeStruct((B * R, L), jnp.float32),
)
bench(f"pallas streamed gather*w, {B}M edges", jax.jit(stream), t2d, idx_big, w_big)

# ---- 5-gather chain per block (window+lane+3-stage permute estimate) ----
def k_chain(t_ref, i_ref, w_ref, o_ref):
    x = jnp.take_along_axis(t_ref[:], i_ref[:], axis=0)
    x = jnp.take_along_axis(x, i_ref[:] % L, axis=1)
    x = jnp.take_along_axis(x, i_ref[:], axis=0)
    x = jnp.take_along_axis(x, i_ref[:] % L, axis=1)
    x = jnp.take_along_axis(x, i_ref[:], axis=0)
    o_ref[:] = w_ref[:] * x

chain = pl.pallas_call(
    k_chain,
    grid=(B,),
    in_specs=[
        pl.BlockSpec((R, L), lambda i: (0, 0)),
        pl.BlockSpec((R, L), lambda i: (i, 0)),
        pl.BlockSpec((R, L), lambda i: (i, 0)),
    ],
    out_specs=pl.BlockSpec((R, L), lambda i: (i, 0)),
    out_shape=jax.ShapeDtypeStruct((B * R, L), jnp.float32),
)
bench(f"pallas 5-gather chain, {B}M edges", jax.jit(chain), t2d, idx_big, w_big)

# ---- XLA full-scale components ----
E = 50_000_000
Nfull = 1_000_000
t_full = jax.device_put(jnp.asarray(rng.random(Nfull, dtype=np.float32)))
src = jax.device_put(jnp.asarray(rng.integers(0, Nfull, E).astype(np.int32)))
w = jax.device_put(jnp.asarray(rng.random(E, dtype=np.float32)))
bench("XLA gather 50M from 1M table", jax.jit(lambda t, s, w: (w * t[s]).max()), t_full, src, w, reps=3)

from protocol_tpu.ops.sparse import rowsum_sorted, power_step_csr
contrib = jax.device_put(jnp.asarray(rng.random(E, dtype=np.float32)))
row_ptr = jax.device_put(jnp.asarray(
    np.searchsorted(np.sort(rng.integers(0, Nfull, E)), np.arange(Nfull + 1)).astype(np.int32)))
bench("XLA rowsum_sorted 50M->1M", jax.jit(lambda c, rp: rowsum_sorted(c, rp).max()), contrib, row_ptr, reps=3)

p = jax.device_put(jnp.full(Nfull, 1.0 / Nfull, np.float32))
dang = jax.device_put(jnp.zeros(Nfull, np.float32))
bench("XLA power_step_csr full scale", jax.jit(
    lambda s, rp, w, t, p, d: power_step_csr(s, rp, w, t, p, d, 0.1).max()),
    src, row_ptr, w, t_full, p, dang, reps=3)
