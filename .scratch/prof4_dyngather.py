"""Verify tpu.dynamic_gather via take_along_axis inside pallas, both axes,
and time the two-step arbitrary gather at scale."""
import time
import jax, jax.numpy as jnp, numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

N = 1 << 20          # table entries
ROWS, LANES = N // 128, 128
TILE = 1024           # sublane rows per grid step (tile = TILE x 128 = 131072 idx)

rng = np.random.default_rng(0)
t2 = jax.device_put(jnp.asarray(rng.random(N, dtype=np.float32).reshape(ROWS, LANES)))
_ = float(jnp.sum(t2))


def bench(name, fn, *args):
    try:
        g = jax.jit(lambda *a: fn(*a).max())
        float(g(*args))
        t0 = time.perf_counter()
        reps = 5
        for _ in range(reps):
            float(g(*args))
        dt = (time.perf_counter() - t0) / reps
        nelem = args[-1].size
        print(f"{name}: {dt*1000:.2f} ms ({nelem/dt/1e9:.2f} Gelem/s)", flush=True)
    except Exception as e:
        print(f"{name}: FAILED — {type(e).__name__}: {str(e).splitlines()[0][:160]}", flush=True)


# --- A: axis-0 gather, idx shape == table shape (ONE call over whole table) ---
r0 = jax.device_put(jnp.asarray(rng.integers(0, ROWS, (ROWS, LANES)).astype(np.int32)))

def k_axis0(t_ref, i_ref, o_ref):
    o_ref[:] = jnp.take_along_axis(t_ref[:], i_ref[:], axis=0)

axis0 = pl.pallas_call(
    k_axis0,
    in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM), pl.BlockSpec(memory_space=pltpu.VMEM)],
    out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
    out_shape=jax.ShapeDtypeStruct((ROWS, LANES), jnp.float32),
)
bench("axis0 full-table (1M idx)", axis0, t2, r0)

# --- B: axis-1 gather (lane select within row), same shape ---
c0 = jax.device_put(jnp.asarray(rng.integers(0, LANES, (ROWS, LANES)).astype(np.int32)))

def k_axis1(t_ref, i_ref, o_ref):
    o_ref[:] = jnp.take_along_axis(t_ref[:], i_ref[:], axis=1)

axis1 = pl.pallas_call(
    k_axis1,
    in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM), pl.BlockSpec(memory_space=pltpu.VMEM)],
    out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
    out_shape=jax.ShapeDtypeStruct((ROWS, LANES), jnp.float32),
)
bench("axis1 full-table (1M idx)", axis1, t2, c0)

# --- C: does idx shape really have to equal table shape? try (TILE,128) vs (8192,128) ---
rsmall = jax.device_put(jnp.asarray(rng.integers(0, ROWS, (TILE, LANES)).astype(np.int32)))

def k_axis0_small(t_ref, i_ref, o_ref):
    o_ref[:] = jnp.take_along_axis(t_ref[:], i_ref[:], axis=0)

axis0s = pl.pallas_call(
    k_axis0_small,
    in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM), pl.BlockSpec(memory_space=pltpu.VMEM)],
    out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
    out_shape=jax.ShapeDtypeStruct((TILE, LANES), jnp.float32),
)
bench("axis0 idx(1024,128) over table(8192,128)", axis0s, t2, rsmall)

# --- D: two-step arbitrary gather, gridded over a 16M-edge stream ---
E = 2**24
r_all = rng.integers(0, ROWS, (E // 128, 128)).astype(np.int32)
c_all = rng.integers(0, LANES, (E // 128, 128)).astype(np.int32)
w_all = rng.random((E // 128, 128), dtype=np.float32)
r_d = jax.device_put(jnp.asarray(r_all))
c_d = jax.device_put(jnp.asarray(c_all))
w_d = jax.device_put(jnp.asarray(w_all))

def k_two_step(t_ref, r_ref, c_ref, w_ref, o_ref):
    v = jnp.take_along_axis(t_ref[:], r_ref[:], axis=0)     # needs idx shape == table shape?
    o_ref[:] = w_ref[:] * jnp.take_along_axis(v, c_ref[:], axis=1)

two = pl.pallas_call(
    k_two_step,
    grid=(E // (ROWS * LANES),),
    in_specs=[
        pl.BlockSpec((ROWS, LANES), lambda i: (0, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((ROWS, LANES), lambda i: (i, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((ROWS, LANES), lambda i: (i, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((ROWS, LANES), lambda i: (i, 0), memory_space=pltpu.VMEM),
    ],
    out_specs=pl.BlockSpec((ROWS, LANES), lambda i: (i, 0), memory_space=pltpu.VMEM),
    out_shape=jax.ShapeDtypeStruct((E // 128, 128), jnp.float32),
)
bench("two-step w*t[src] 16M edges", two, t2, r_d, c_d, w_d)
