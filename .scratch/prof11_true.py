"""LICM-defeated component breakdown + in-register primitive costs.

Every loop body depends on the carry so WhileLoopInvariantCodeMotion
cannot hoist the op being measured.
"""
import sys, time
sys.path.insert(0, "/root/repo")
import jax, jax.numpy as jnp, numpy as np
from jax import lax
from jax.experimental import pallas as pl
from protocol_tpu.ops.sparse import rowsum_sorted

rng = np.random.default_rng(0)
E, N = 50_000_000, 1_000_000
REPS = 8


def timeit(name, fn, *args, reps=2, per=REPS):
    f = jax.jit(fn)
    r = np.asarray(jax.tree.leaves(f(*args))[0])
    t0 = time.perf_counter()
    for _ in range(reps):
        r = np.asarray(jax.tree.leaves(f(*args))[0])
    dt = (time.perf_counter() - t0) / reps
    print(f"{name}: {dt/per*1e3:.2f} ms/iter  ({dt*1e3:.0f} ms total)", flush=True)


t_full = jax.device_put(jnp.asarray(rng.random(N, dtype=np.float32)))
src = jax.device_put(jnp.asarray(rng.integers(0, N, E).astype(np.int32)))
w = jax.device_put(jnp.asarray(rng.random(E, dtype=np.float32)))
contrib = jax.device_put(jnp.asarray(rng.random(E, dtype=np.float32)))
row_ptr = jax.device_put(jnp.asarray(
    np.searchsorted(np.sort(rng.integers(0, N, E)), np.arange(N + 1)).astype(np.int32)))

EPS = jnp.float32(1e-38)

def dep_chain(body):
    """body(x_perturbed, *args) -> array; carry a scalar that perturbs
    the input each iteration so nothing is loop-invariant."""
    def run(*args):
        def step(_, acc):
            return body(acc * EPS, *args)
        return lax.fori_loop(0, REPS, step, jnp.float32(0))
    return run

timeit("gather 50M (dep)", dep_chain(lambda d, t, s: (t + d)[s].max()), t_full, src)
timeit("w*gather 50M (dep)", dep_chain(lambda d, t, s, w: (w * (t + d)[s]).max()), t_full, src, w)
timeit("rowsum_sorted 50M (dep)", dep_chain(
    lambda d, c, rp: rowsum_sorted(c + d, rp).max()), contrib, row_ptr)
timeit("50M elementwise mul (dep)", dep_chain(lambda d, c, w: ((c + d) * w).max()), contrib, w)

# in-register primitive costs: K chained gathers on one vreg inside a kernel
K = 512
idxc = jax.device_put(jnp.asarray(rng.integers(0, 128, (8, 128)).astype(np.int32)))

def k_lane(i_ref, o_ref):
    x = i_ref[:]
    for _ in range(K):
        x = jnp.take_along_axis(idx_tbl, x, axis=1)
    o_ref[:] = x

idx_tbl_np = rng.integers(0, 128, (8, 128)).astype(np.int32)
idx_tbl = jnp.asarray(idx_tbl_np)

lane_k = pl.pallas_call(k_lane, out_shape=jax.ShapeDtypeStruct((8, 128), jnp.int32))
try:
    timeit(f"lane-gather x{K} on one vreg", lambda i: lane_k(i), idxc, per=K, reps=3)
except Exception as e:
    print(f"lane chain: FAILED {type(e).__name__}: {str(e).splitlines()[0][:160]}", flush=True)

def k_sub(i_ref, o_ref):
    x = i_ref[:]
    for _ in range(K):
        x = jnp.take_along_axis(idx_tbl8, x % 8, axis=0)
    o_ref[:] = x

idx_tbl8 = jnp.asarray(rng.integers(0, 128, (8, 128)).astype(np.int32))
sub_k = pl.pallas_call(k_sub, out_shape=jax.ShapeDtypeStruct((8, 128), jnp.int32))
try:
    timeit(f"sublane-gather x{K} on one vreg", lambda i: sub_k(i), idxc, per=K, reps=3)
except Exception as e:
    print(f"sublane chain: FAILED {type(e).__name__}: {str(e).splitlines()[0][:160]}", flush=True)
