import time, jax, jax.numpy as jnp, numpy as np

E, N = 50_000_000, 1_000_000
rng = np.random.default_rng(0)
dst = np.sort(rng.integers(0, N, E).astype(np.int32))
w = rng.random(E, dtype=np.float32)
t = rng.random(N, dtype=np.float32)
srcr = rng.integers(0, N, E).astype(np.int32)

src_d = jax.device_put(jnp.asarray(srcr))
dst_d = jax.device_put(jnp.asarray(dst))
w_d = jax.device_put(jnp.asarray(w))
t_d = jax.device_put(jnp.asarray(t))
_ = float(jnp.sum(w_d))  # drain transfers

def timeit(name, f, *a):
    g = jax.jit(f)
    float(g(*a))
    t0 = time.perf_counter()
    reps = 3
    for _ in range(reps):
        r = float(g(*a))
    dt = (time.perf_counter() - t0) / reps
    print(f"{name}: {dt*1000:.1f} ms")

timeit("reduce max(w) [read 200MB]", lambda w: w.max(), w_d)
timeit("max(w*w2) [read 400MB]", lambda w: (w*jnp.flip(w)).max(), w_d)
timeit("gather max(t[src])", lambda t, s: t[s].max(), t_d, src_d)
timeit("gather+mul max(w*t[src])", lambda t, s, w: (w * t[s]).max(), t_d, src_d, w_d)
timeit("segsum max", lambda w, d: jax.ops.segment_sum(w, d, num_segments=N, indices_are_sorted=True).max(), w_d, dst_d)
timeit("full COO step max", lambda t, s, d, w: jax.ops.segment_sum(w * t[s], d, num_segments=N, indices_are_sorted=True).max(), t_d, src_d, dst_d, w_d)
