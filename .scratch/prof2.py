import time, jax, jax.numpy as jnp, numpy as np

E, N = 50_000_000, 1_000_000
rng = np.random.default_rng(0)
w = rng.random(E, dtype=np.float32)
t = rng.random(N, dtype=np.float32)
src_sorted = np.sort(rng.integers(0, N, E).astype(np.int32))
perm = rng.permutation(E).astype(np.int32)

w_d = jax.device_put(jnp.asarray(w))
t_d = jax.device_put(jnp.asarray(t))
ss_d = jax.device_put(jnp.asarray(src_sorted))
perm_d = jax.device_put(jnp.asarray(perm))
_ = float(jnp.sum(w_d))

def timeit(name, f, *a):
    g = jax.jit(f)
    float(g(*a))
    t0 = time.perf_counter(); reps=3
    for _ in range(reps): float(g(*a))
    print(f"{name}: {(time.perf_counter()-t0)/reps*1000:.1f} ms")

import jax.lax as lax
timeit("sorted gather t[src_sorted]", lambda t,s: jnp.take(t, s, indices_are_sorted=True).max(), t_d, ss_d)
timeit("fixed perm w[perm]", lambda w,p: w[p].max(), w_d, perm_d)
timeit("cumsum 50M f32", lambda w: jnp.cumsum(w).max(), w_d)
timeit("assoc_scan add 50M", lambda w: lax.associative_scan(lambda a,b: a+b, w).max(), w_d)
from protocol_tpu.ops.sparse import rowsum_sorted
row_ptr = jax.device_put(jnp.asarray(np.searchsorted(src_sorted, np.arange(N+1)).astype(np.int32)))
timeit("rowsum_sorted (CSR cumsum)", lambda w,rp: rowsum_sorted(w, rp).max(), w_d, row_ptr)
