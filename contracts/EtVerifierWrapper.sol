// SPDX-License-Identifier: MIT
pragma solidity ^0.8.17;

/// @notice Thin wrapper forwarding (pub_ins ‖ proof) calldata to a raw
/// PLONK verifier contract via staticcall — the on-chain entry point the
/// client's `verify` subcommand transacts with. Equivalent role to the
/// reference wrapper around its generated Yul verifier; written with
/// high-level calldata assembly-free forwarding and custom errors.
contract EtVerifierWrapper {
    error VerifierMissing();
    error VerificationFailed();

    /// Raw verifier contract (e.g. a deployed Yul PLONK verifier whose
    /// calldata layout is uint256[N] public inputs followed by the
    /// proof bytes).
    address public immutable verifier;

    uint256 public constant NUM_PUB_INS = 5;

    event Verified(address indexed caller);

    constructor(address verifier_) {
        verifier = verifier_;
    }

    function verify(
        uint256[NUM_PUB_INS] calldata pubIns,
        bytes calldata proof
    ) external {
        if (verifier.code.length == 0) revert VerifierMissing();
        (bool ok, ) = verifier.staticcall(
            abi.encodePacked(pubIns, proof)
        );
        if (!ok) revert VerificationFailed();
        emit Verified(msg.sender);
    }
}
