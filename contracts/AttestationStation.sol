// SPDX-License-Identifier: MIT
pragma solidity ^0.8.17;

/// @notice Minimal attestation registry in the Optimism AttestationStation
/// shape: a (creator, about, key) => bytes store whose AttestationCreated
/// events are the protocol's entire peer-to-peer transport (the node
/// replays them from block 0). Functionally equivalent to the reference
/// registry; rewritten with calldata arrays and custom errors.
contract AttestationStation {
    mapping(address => mapping(address => mapping(bytes32 => bytes)))
        public attestations;

    struct AttestationData {
        address about;
        bytes32 key;
        bytes val;
    }

    event AttestationCreated(
        address indexed creator,
        address indexed about,
        bytes32 indexed key,
        bytes val
    );

    /// @notice Record a batch of attestations under msg.sender.
    function attest(AttestationData[] calldata batch) external {
        for (uint256 i = 0; i < batch.length; ++i) {
            AttestationData calldata a = batch[i];
            attestations[msg.sender][a.about][a.key] = a.val;
            emit AttestationCreated(msg.sender, a.about, a.key, a.val);
        }
    }
}
