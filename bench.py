"""Headline benchmark: 1M-peer / 50M-edge global-trust convergence.

BASELINE.md config 4: scale-free graph, row-normalized sparse
transpose-SpMV power iteration with pre-trust damping, fixed 40
iterations (the reference's production loop runs a fixed iteration count,
server NUM_ITER=10 at N=5; 40 covers 1e-6-level convergence at this
scale).  The reference publishes no numbers (BASELINE.md) — the driver
target is "<2 s on a v5e-8"; this runs on however many chips are visible
(one, under the tunnel) and reports wall-clock for the full convergence,
excluding one-time compile + host->HBM transfer of the graph.

Prints ONE JSON line: metric/value/unit/vs_baseline where vs_baseline =
target_seconds / measured_seconds (>1 beats the 2 s target).
"""

from __future__ import annotations

import json
import time


def main() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from protocol_tpu.models.graphs import scale_free
    from protocol_tpu.ops.sparse import converge_csr
    from protocol_tpu.trust.graph import TrustGraph

    n_peers = 1_000_000
    n_edges = 50_000_000
    iters = 40
    target_seconds = 2.0

    graph = scale_free(n_peers, n_edges, seed=7)
    g = graph.drop_self_edges()
    w, dangling = g.row_normalized()
    g = TrustGraph(g.n, g.src, g.dst, w, graph.pre_trusted).sorted_by_dst()
    p = graph.pre_trust_vector()

    device_args = (
        jax.device_put(jnp.asarray(g.src)),
        jax.device_put(jnp.asarray(g.row_ptr_by_dst())),
        jax.device_put(jnp.asarray(g.weight)),
        jax.device_put(jnp.asarray(p)),
        jax.device_put(jnp.asarray(p)),
        jax.device_put(jnp.asarray(dangling.astype(np.float32))),
    )
    jax.block_until_ready(device_args)

    def run():
        t, it, resid = converge_csr(
            *device_args, alpha=jnp.float32(0.1), tol=0.0, max_iter=iters
        )
        # Force a host transfer: on the tunneled single-chip platform
        # block_until_ready can return before the computation drains, so
        # timing must include materialising the result on the host (the
        # 4 MB score-vector copy is noise next to the compute).
        return np.asarray(t)

    run()  # compile + warm up
    t0 = time.perf_counter()
    scores = run()
    elapsed = time.perf_counter() - t0
    assert abs(scores.sum() - 1.0) < 1e-3

    print(
        json.dumps(
            {
                "metric": "1M-peer/50M-edge global-trust convergence wall-clock (40 power iters)",
                "value": round(elapsed, 4),
                "unit": "seconds",
                "vs_baseline": round(target_seconds / elapsed, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
