"""Benchmarks: the headline 1M-peer convergence plus the full
BASELINE.md five-config ladder.

Default mode (what the driver runs) prints ONE JSON line for config 4 —
the 1M-peer / 50M-edge scale-free convergence on the fused windowed
pipeline (``tpu-windowed``, PERF.md §7), 40 fixed power iterations,
wall-clock excluding compile, host->HBM transfer, and the one-time
bucketing plan (reported separately as ``plan_seconds``).  The previous
headline kernel stays reachable via ``--backend tpu-csr`` to reproduce
the 17.9 s PERF.md §1 number.  The reference publishes no numbers
(BASELINE.md); the driver target is "< 2 s on a v5e-8" and this runs on
however many chips are visible (one, under the tunnel).

``--ladder`` runs all five BASELINE.md configs, prints one JSON report
with five entries (plus the same headline line last, so driver parsing
keeps working), and persists the report as ``LADDER_r<N>.json``
(``--ladder-out`` overrides).  Config 3 runs a *synthetic* scale-free
stand-in at the OP-mainnet snapshot's sparsity class — no real snapshot
ships in this image — and its metric says so.  ``--scale-div N``
divides every ladder config's size by N (CI smoke runs on CPU).
``--backend tpu-sharded:tpu-windowed`` runs the headline on the fused
pipeline sharded across the visible mesh (PERF.md §8).

Per-iteration cost model and kernel-selection evidence: PERF.md.
"""

from __future__ import annotations

import argparse
import json
import time


def _timed(fn):
    t0 = time.perf_counter()
    out = fn()
    return out, time.perf_counter() - t0


def _peak_memory_bytes(compiled) -> int | None:
    """Per-device peak HBM of one AOT executable from its buffer
    assignment (graftlint pass-12 view): resident arguments + temp
    arena + unaliased outputs.  None where the runtime exposes no
    memory analysis."""
    try:
        ma = compiled.memory_analysis()
    except Exception:  # noqa: BLE001 - absent on some runtimes
        return None
    if ma is None:
        return None
    return int(
        ma.argument_size_in_bytes
        + ma.output_size_in_bytes
        - ma.alias_size_in_bytes
        + ma.temp_size_in_bytes
    )


def headline_entry(
    iters: int = 40,
    backend: str = "tpu-windowed",
    n_peers: int = 1_000_000,
    n_edges: int = 50_000_000,
) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from protocol_tpu.models.graphs import scale_free
    from protocol_tpu.obs import TRACER
    from protocol_tpu.ops.gather_window import build_window_plan, converge_windowed
    from protocol_tpu.ops.sparse import converge_csr
    from protocol_tpu.trust.graph import TrustGraph

    target_seconds = 2.0

    graph = scale_free(n_peers, n_edges, seed=7)
    g = graph.drop_self_edges()
    w, dangling = g.row_normalized()
    g = TrustGraph(g.n, g.src, g.dst, w, graph.pre_trusted).sorted_by_dst()
    p = graph.pre_trust_vector()
    extra: dict = {}
    # Span-derived phase timings (ISSUE 4): the bench emits the SAME
    # obs spans the node's epoch tick does (plan, converge), so a
    # BENCH_*.json line and a production /trace/<epoch> use identical
    # phase names.
    phases: dict = {}

    if backend == "tpu-csr":
        device_args = (
            jax.device_put(jnp.asarray(g.src)),
            jax.device_put(jnp.asarray(g.row_ptr_by_dst())),
            jax.device_put(jnp.asarray(g.weight)),
            jax.device_put(jnp.asarray(p)),
            jax.device_put(jnp.asarray(dangling.astype(np.float32))),
        )
        alpha = jax.device_put(np.float32(0.1))
        jax.block_until_ready(device_args)
        # Pass-12 memory scrape (PERF.md §19): per-device peak HBM of
        # the exact module this bench executes, from the AOT buffer
        # assignment — compiled once, outside the timed region, like
        # the comm scrape.
        extra["peak_memory_bytes"] = _peak_memory_bytes(
            converge_csr.lower(
                device_args[0], device_args[1], device_args[2],
                jax.device_put(jnp.asarray(p)), device_args[3],
                device_args[4], alpha=alpha, tol=0.0, max_iter=iters,
            ).compile()
        )

        def run():
            # t0 is donated by converge_csr: stage a fresh buffer per
            # call (4 MB host->HBM, noise next to the compute).
            t0 = jax.device_put(jnp.asarray(p))
            t, it, resid = converge_csr(
                device_args[0],
                device_args[1],
                device_args[2],
                t0,
                device_args[3],
                device_args[4],
                alpha=alpha,
                tol=0.0,
                max_iter=iters,
            )
            # Force a host transfer: on the tunneled single-chip
            # platform block_until_ready can return before the
            # computation drains, so timing must include materialising
            # the result on the host (the 4 MB score-vector copy is
            # noise next to the compute).
            return np.asarray(t)

    elif backend == "tpu-windowed":
        # One-time static plan: excluded from the per-iteration metric
        # (it amortizes across epochs and reboots via the checkpoint
        # store) but reported so regressions in host bucketing show up.
        with TRACER.span("plan", backend=backend) as plan_span:
            plan, plan_dt = _timed(
                lambda: build_window_plan(g.src, g.dst, g.weight, n=g.n)
            )
        phases["plan"] = round(plan_span.duration_s or 0.0, 4)
        interpret = jax.default_backend() != "tpu"
        device_args = tuple(jax.device_put(a) for a in plan.device_args()) + (
            jax.device_put(jnp.asarray(p)),
            jax.device_put(jnp.asarray(dangling.astype(np.float32))),
        )
        alpha = jax.device_put(np.float32(0.1))
        jax.block_until_ready(device_args)
        extra = {
            "plan_seconds": round(plan_dt, 4),
            "bridge_segments": plan.n_segments,
            "bridge_compression": round(plan.compression, 2),
        }
        # Pass-12 memory scrape: AOT buffer-assignment peak of the
        # module this bench executes, outside the timed region.
        extra["peak_memory_bytes"] = _peak_memory_bytes(
            converge_windowed.lower(
                *device_args[:7],
                jax.device_put(jnp.asarray(p)),
                *device_args[7:],
                n_rows=plan.n_rows,
                table_entries=plan.table_entries,
                alpha=alpha,
                tol=0.0,
                max_iter=iters,
                interpret=interpret,
            ).compile()
        )

        def run():
            # t0 is donated by converge_windowed: fresh buffer per call.
            t0 = jax.device_put(jnp.asarray(p))
            t, it, resid = converge_windowed(
                *device_args[:7],
                t0,
                *device_args[7:],
                n_rows=plan.n_rows,
                table_entries=plan.table_entries,
                alpha=alpha,
                tol=0.0,
                max_iter=iters,
                interpret=interpret,
            )
            return np.asarray(t)

    elif backend == "tpu-sharded:tpu-windowed":
        # The fused pipeline taken multi-chip (PERF.md §8): window rows
        # partitioned across the default mesh, per-shard windowed step
        # under shard_map, boundary dst rows completed by psum.  On the
        # single-chip tunnel this measures the Mesh(1) overhead floor;
        # on a v5e-8 it is the headline multi-chip number.
        from protocol_tpu.parallel.mesh import SHARD_AXIS, default_mesh
        from protocol_tpu.parallel.sharded import ShardedWindowPlan, converge_sharded

        mesh = default_mesh()
        with TRACER.span("plan", backend=backend) as plan_span:
            swp, plan_dt = _timed(lambda: ShardedWindowPlan.build(graph, mesh))
        phases["plan"] = round(plan_span.duration_s or 0.0, 4)
        extra = {
            "plan_seconds": round(plan_dt, 4),
            "bridge_segments": swp.plan.n_segments,
            "bridge_compression": round(swp.plan.compression, 2),
            "mesh_shards": int(mesh.shape[SHARD_AXIS]),
            "rows_per_shard": swp.rows_per_shard,
        }
        # Pass-8 comm scrape (PERF.md §15): per-iteration collective
        # byte volume of the exact module this bench executes, recorded
        # into the LADDER round so tools/perf_sentinel.py tracks it as
        # a comm_bytes_per_iter series.  AOT-compiled once, outside the
        # timed region.
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        from protocol_tpu.analysis.comm.hlo_walk import parse_module
        from protocol_tpu.parallel.sharded import _get_windowed_runner

        runner = _get_windowed_runner(
            mesh, swp.n, swp.rows_per_shard, swp.table_entries, swp.interpret
        )
        alpha_repl = jax.device_put(np.float32(0.1), NamedSharding(mesh, P()))
        compiled = runner.lower(
            swp.wid, swp.local, swp.weight, swp.seg_end, swp.seg_first,
            swp.seg_perm, swp.dst_ptr, swp.t0(), swp.p, swp.dangling,
            alpha_repl, max_iter=iters, tol=0.0,
        ).compile()
        mod = parse_module(compiled.as_text())
        extra["comm_bytes_per_iter"] = mod.total_bytes(per_iteration_only=True)
        extra["comm_collectives"] = mod.kind_counts()
        # Pass-12 memory scrape: per-SHARD peak HBM (memory_analysis is
        # the per-device view) of the same executable.
        extra["peak_memory_bytes"] = _peak_memory_bytes(compiled)

        def run():
            t, it, resid = converge_sharded(swp, alpha=0.1, tol=0.0, max_iter=iters)
            return np.asarray(t)

    else:
        raise ValueError(
            "headline backend must be tpu-windowed, tpu-csr, or "
            f"tpu-sharded:tpu-windowed, got {backend!r}"
        )

    run()  # compile + warm up
    t0 = time.perf_counter()
    with TRACER.span("converge", backend=backend):
        scores = run()
    elapsed = time.perf_counter() - t0
    phases["converge"] = round(elapsed, 4)
    assert abs(scores.sum() - 1.0) < 1e-3

    return {
        "metric": f"1M-peer/50M-edge global-trust convergence wall-clock (40 power iters, {backend})",
        "value": round(elapsed, 4),
        "unit": "seconds",
        "n_hosts": 1,
        "vs_baseline": round(target_seconds / elapsed, 3),
        "phases": phases,
        **extra,
    }


def epochs_entry(
    epochs: int = 5,
    churn: float = 0.01,
    backend: str = "tpu-windowed",
    n_peers: int = 1_000_000,
    n_edges: int = 50_000_000,
    tol: float = 1e-6,
    max_iter: int = 60,
    seed: int = 7,
) -> dict:
    """Multi-epoch steady-state benchmark (PERF.md §11, ISSUE 5).

    Epoch 0 runs the cold path: full ``WindowPlan`` build plus a
    cold-start convergence from the pre-trust vector.  Every later
    epoch replays ``churn``·E edges of *sender-centric* churn — a
    recency-biased cohort of peers re-attests, each rewriting its whole
    out-row (row normalization makes the row the atomic delta unit) —
    and runs the steady-state path: plan delta
    (``WindowPlan.apply_delta`` via the backend's ``delta_rows`` hint)
    + warm-start convergence from the previous fixed point.  The
    recency bias mirrors production id assignment: manager peer ids are
    first-seen order, so the churning cohort (recently joined / most
    active users) is id-local, keeping the delta's touched windows far
    below the window count (the delta/rebuild crossover, PERF.md §11).

    The cold number excludes compile (one discarded warm-up converge,
    same policy as the headline); the per-epoch numbers are pure
    plan-update + converge wall-clock.  Correctness is pinned by
    cold-converging the FINAL churned graph on a fresh backend and
    requiring the warm scores to match within the convergence
    tolerance.
    """
    import numpy as np

    from protocol_tpu.models.churn import churn_cohort_dims, sender_centric_churn
    from protocol_tpu.models.graphs import scale_free
    from protocol_tpu.obs.metrics import PLAN_OUTCOMES
    from protocol_tpu.trust.backend import get_backend

    rng = np.random.default_rng(seed)
    graph = scale_free(n_peers, n_edges, seed=seed).drop_self_edges()
    b = get_backend(backend)

    # Pre-build the plan so epoch 0 separates plan cost from converge
    # cost, and a throwaway converge eats the jit compile.
    plan_seconds = 0.0
    if hasattr(b, "plan"):
        from protocol_tpu.ops.gather_window import build_window_plan

        w, _ = graph.row_normalized()
        plan, plan_seconds = _timed(
            lambda: build_window_plan(graph.src, graph.dst, w, n=graph.n)
        )
        b.plan = plan
    b.converge(graph, alpha=0.1, tol=tol, max_iter=max_iter)  # compile
    res0, cold_converge = _timed(
        lambda: b.converge(graph, alpha=0.1, tol=tol, max_iter=max_iter)
    )
    cold_epoch_seconds = plan_seconds + cold_converge

    per_epoch = []
    scores = res0.scores
    cur = graph
    delta0 = PLAN_OUTCOMES.value(outcome="delta")
    rebuild0 = PLAN_OUTCOMES.value(outcome="rebuild")
    cohort_size, deg = churn_cohort_dims(cur, churn)
    for k in range(1, epochs):
        # Recency-biased re-attesting cohort (models.churn — the
        # shared sender-centric stream the pod dryrun replays too).
        rows, cur, _ = sender_centric_churn(
            rng, cur, cohort_size=cohort_size, deg=deg
        )
        if hasattr(b, "delta_rows"):
            b.delta_rows = rows
        res, dt = _timed(
            lambda: b.converge(cur, alpha=0.1, tol=tol, max_iter=max_iter, t0=scores)
        )
        scores = res.scores
        per_epoch.append(
            {"epoch": k, "seconds": round(dt, 4), "iterations": res.iterations}
        )

    # Correctness pin: a fresh backend cold-converges the final graph.
    ref = get_backend(backend).converge(cur, alpha=0.1, tol=tol, max_iter=max_iter)
    warm_vs_cold_l1 = float(np.abs(scores - ref.scores).sum())

    steady = sorted(e["seconds"] for e in per_epoch)
    steady_state_epoch_seconds = steady[len(steady) // 2] if steady else 0.0
    warm_iters = [e["iterations"] for e in per_epoch]
    return {
        "metric": (
            f"steady-state epoch wall-clock (plan update + converge) at "
            f"{churn:.2%} churn/epoch, {n_peers} peers / {n_edges} edges, {backend}"
        ),
        "value": round(steady_state_epoch_seconds, 4),
        "unit": "seconds",
        "n_hosts": 1,
        "epochs": epochs,
        "churn": churn,
        "cold_epoch_seconds": round(cold_epoch_seconds, 4),
        "steady_state_epoch_seconds": round(steady_state_epoch_seconds, 4),
        "cold_vs_steady_speedup": round(
            cold_epoch_seconds / max(steady_state_epoch_seconds, 1e-9), 2
        ),
        "plan_seconds": round(plan_seconds, 4),
        "cold_iterations": int(ref.iterations),
        "warm_iterations_mean": round(sum(warm_iters) / max(len(warm_iters), 1), 2),
        "iterations_saved_by_warm_start": round(
            ref.iterations - sum(warm_iters) / max(len(warm_iters), 1), 2
        ),
        "plan_outcomes": {
            "delta": PLAN_OUTCOMES.value(outcome="delta") - delta0,
            "rebuild": PLAN_OUTCOMES.value(outcome="rebuild") - rebuild0,
        },
        "warm_vs_cold_l1": warm_vs_cold_l1,
        "per_epoch": per_epoch,
    }


def ladder(scale_div: int = 1, iters: int = 40, backend: str = "tpu-windowed") -> list[dict]:
    """The five BASELINE.md configs.

    Configs 1-3 and 5 time one ``backend.converge`` call after a warm-up
    call has compiled the kernel — the timed region therefore includes
    host-side normalization/sorting and the host->device transfer (the
    backend API bundles them), unlike the headline config 4 which
    pre-stages device arrays and times only the iteration loop.
    ``iters`` scales the per-config iteration count (tests shrink it)."""
    from pathlib import Path

    import numpy as np

    from protocol_tpu.models.graphs import erdos_renyi, scale_free, sybil_mass, sybil_stress
    from protocol_tpu.node.bootstrap import read_bootstrap_csv
    from protocol_tpu.trust.backend import get_backend
    from protocol_tpu.trust.graph import TrustGraph

    entries: list[dict] = []

    def converge_timed(backend, graph, *, warm=True, **kw):
        b = get_backend(backend)
        if warm:
            b.converge(graph, **kw)  # compile
        res, dt = _timed(lambda: b.converge(graph, **kw))
        return res, dt

    # -- config 1: bootstrap set, 5 peers, native CPU parity ------------
    nodes = read_bootstrap_csv(Path(__file__).resolve().parent / "data" / "bootstrap-nodes.csv")
    n1 = len(nodes)
    ops = np.full((n1, n1), 200.0, np.float32)
    np.fill_diagonal(ops, 0.0)
    g1 = TrustGraph.from_dense(ops)
    res1, dt1 = converge_timed("native-cpu", g1, warm=False, alpha=0.0, tol=0.0, max_iter=10)
    # Reference parity: uniform opinions converge to uniform scores
    # (manager/mod.rs:246-262 initial-attestation test semantics).
    assert np.allclose(res1.scores, 1.0 / n1, atol=1e-12)
    entries.append(
        {
            "config": "1-bootstrap-5peer-native-cpu",
            "metric": "5-peer exact dense power iteration (10 iters)",
            "value": round(dt1, 5),
            "unit": "seconds",
            "power_iters_per_sec": round(10 / dt1, 1),
        }
    )

    # -- config 2: 10k dense jnp.matmul ---------------------------------
    n2 = 10_000 // scale_div
    g2 = erdos_renyi(n2, avg_degree=100.0, seed=11)
    res2, dt2 = converge_timed("tpu-dense", g2, alpha=0.1, tol=0.0, max_iter=iters)
    entries.append(
        {
            "config": "2-erdos-renyi-10k-dense",
            "metric": f"{n2}-peer dense matmul convergence ({iters} iters)",
            "value": round(dt2, 4),
            "unit": "seconds",
            "power_iters_per_sec": round(iters / dt2, 2),
        }
    )

    # -- config 3: synthetic stand-in at snapshot sparsity, BCOO SpMV ---
    # No OP-mainnet snapshot ships in this image; a SYNTHETIC scale-free
    # graph at the snapshot's sparsity class (avg degree ~20) stands in,
    # and the output says so (VERDICT item #5) — the number is the
    # kernel's wall-clock at that shape, not a real-snapshot replay.
    n3, e3 = 100_000 // scale_div, 2_000_000 // scale_div
    g3 = scale_free(n3, e3, seed=13)
    res3, dt3 = converge_timed("tpu-sparse", g3, alpha=0.1, tol=0.0, max_iter=iters)
    entries.append(
        {
            "config": "3-synthetic-standin-sparsity-bcoo",
            "metric": (
                f"{n3}-peer/{e3}-edge sparse SpMV convergence ({iters} iters) "
                "on a synthetic scale-free stand-in (no OP-mainnet snapshot "
                "in image)"
            ),
            "value": round(dt3, 4),
            "unit": "seconds",
            "power_iters_per_sec": round(iters / dt3, 2),
        }
    )

    # -- config 4: the headline (1M/50M, fused windowed by default) -----
    if scale_div == 1:
        entries.append({"config": f"4-scale-free-1M-{backend}", **headline_entry(backend=backend)})
    else:
        n4, e4 = 1_000_000 // scale_div, 50_000_000 // scale_div
        g4 = scale_free(n4, e4, seed=7)
        res4, dt4 = converge_timed(backend, g4, alpha=0.1, tol=0.0, max_iter=iters)
        entries.append(
            {
                "config": f"4-scale-free-1M-{backend}",
                "metric": f"{n4}-peer/{e4}-edge {backend} convergence ({iters} iters)",
                "value": round(dt4, 4),
                "unit": "seconds",
                "power_iters_per_sec": round(iters / dt4, 2),
            }
        )

    # -- config 5: 10M-peer sybil stress, damping sweep -----------------
    n5, e5 = 10_000_000 // scale_div, 50_000_000 // scale_div
    frac = 0.3
    g5 = sybil_stress(n5, e5, sybil_fraction=frac, seed=17)
    sweep = []
    b5 = get_backend("tpu-csr")
    b5.converge(g5, alpha=0.1, tol=0.0, max_iter=iters)  # compile once
    t0 = time.perf_counter()
    for alpha in (0.0, 0.05, 0.1, 0.2, 0.3):
        res = b5.converge(g5, alpha=alpha, tol=0.0, max_iter=iters)
        sweep.append(
            {
                "alpha": alpha,
                "sybil_mass": round(sybil_mass(res.scores, n5, frac), 5),
            }
        )
    dt5 = time.perf_counter() - t0
    # Damping must monotonically squeeze the collective's captured mass.
    masses = [s["sybil_mass"] for s in sweep]
    assert all(a >= b - 1e-6 for a, b in zip(masses, masses[1:])), masses
    entries.append(
        {
            "config": "5-sybil-stress-10M-damping-sweep",
            "metric": f"{n5}-peer/{e5}-edge 30%-sybil damping sweep (5 alphas x {iters} iters)",
            "value": round(dt5, 4),
            "unit": "seconds",
            "power_iters_per_sec": round(5 * iters / dt5, 2),
            "sybil_mass_curve": sweep,
        }
    )
    return entries


def _next_round_path() -> str:
    """``LADDER_r<N>.json`` with N following the highest recorded
    BENCH/LADDER round, so ladder reports land next to the driver's
    bench history without clobbering earlier rounds."""
    import re
    from pathlib import Path

    here = Path(__file__).resolve().parent
    rounds = [0]
    for p in here.glob("*_r*.json"):
        m = re.fullmatch(r"(?:BENCH|LADDER)_r(\d+)\.json", p.name)
        if m:
            rounds.append(int(m.group(1)))
    return str(here / f"LADDER_r{max(rounds) + 1:02d}.json")


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--ladder", action="store_true", help="run all 5 BASELINE configs")
    ap.add_argument("--scale-div", type=int, default=1, help="divide ladder sizes (CI smoke)")
    ap.add_argument(
        "--ladder-out",
        default=None,
        help="path for the --ladder JSON report (default: LADDER_r<N>.json "
        "with N = next round after the recorded BENCH/LADDER files)",
    )
    ap.add_argument(
        "--backend",
        default="tpu-windowed",
        choices=["tpu-windowed", "tpu-csr", "tpu-sharded:tpu-windowed"],
        help="headline (config 4) kernel: the fused windowed pipeline "
        "(default, PERF.md §7), the previous CSR/cumsum formulation, or "
        "the mesh-sharded windowed pipeline (PERF.md §8)",
    )
    ap.add_argument(
        "--platform",
        default=None,
        help="force a JAX platform (e.g. cpu for smoke runs); the site "
        "hook pins the tunnel platform at interpreter start, so the env "
        "var alone is not enough — this applies the config override the "
        "way tests/conftest.py does",
    )
    ap.add_argument(
        "--epochs",
        type=int,
        default=None,
        help="multi-epoch steady-state benchmark: epoch 0 cold (full "
        "plan build + cold converge), then N-1 churned epochs on the "
        "steady-state path (plan delta + warm start); prints one JSON "
        "line with steady_state_epoch_seconds and "
        "iterations_saved_by_warm_start next to the cold number",
    )
    ap.add_argument(
        "--churn",
        type=float,
        default=0.01,
        help="edge fraction rewired per steady-state epoch (with --epochs)",
    )
    ap.add_argument(
        "--peers", type=int, default=1_000_000, help="graph size for --epochs"
    )
    ap.add_argument(
        "--edges", type=int, default=50_000_000, help="edge count for --epochs"
    )
    args = ap.parse_args()

    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)

    if args.epochs is not None:
        print(
            json.dumps(
                epochs_entry(
                    epochs=args.epochs,
                    churn=args.churn,
                    backend=args.backend,
                    n_peers=args.peers,
                    n_edges=args.edges,
                )
            )
        )
        return

    if args.ladder:
        entries = ladder(scale_div=args.scale_div, backend=args.backend)
        report = {"ladder": entries, "scale_div": args.scale_div}
        print(json.dumps(report, indent=2))
        # Persist the full ladder as LADDER_r<N>.json (VERDICT item #5)
        # so every recorded round keeps its five wall-clocks.
        out_path = args.ladder_out or _next_round_path()
        with open(out_path, "w") as f:
            json.dump(report, f, indent=2)
            f.write("\n")
        print(f"ladder report written to {out_path}", flush=True)
        # Driver-parsable single line, last.
        headline = next(e for e in entries if e["config"].startswith("4-"))
        line = {k: headline[k] for k in ("metric", "value", "unit") if k in headline}
        if "vs_baseline" in headline:
            line["vs_baseline"] = headline["vs_baseline"]
        print(json.dumps(line))
        return

    print(json.dumps(headline_entry(backend=args.backend)))


if __name__ == "__main__":
    main()
